"""Broker-side reporter agent: registry → agent loop → transport →
sampler → aggregator → model build (the reference's
CruiseControlMetricsReporterTest + ContainerMetricUtils coverage)."""

import os
import time

import numpy as np
import pytest

from cruise_control_tpu.common.resources import Resource
from cruise_control_tpu.config.cruise_control_config import CruiseControlConfig
from cruise_control_tpu.executor.admin import InMemoryAdminBackend, PartitionState
from cruise_control_tpu.metricdef.raw_metric_type import RawMetricType as R
from cruise_control_tpu.model.tensors import broker_load
from cruise_control_tpu.monitor import LoadMonitor, ModelCompletenessRequirements
from cruise_control_tpu.monitor.sampling import (
    CruiseControlMetricsReporterSampler, InMemoryMetricsTransport,
)
from cruise_control_tpu.reporter import (
    BrokerMetricsRegistry, MetricsReporterAgent, cgroup_cpu_cores,
    container_cpu_util, deserialize,
)


# ---- container awareness ---------------------------------------------------

def _write(root, rel, content):
    path = os.path.join(root, rel)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        f.write(content)


def test_cgroup_v2_quota(tmp_path):
    _write(str(tmp_path), "cpu.max", "200000 100000\n")
    assert cgroup_cpu_cores(str(tmp_path), host_cores=64) == 2.0
    # 3% of a 64-core host = 96% of a 2-core allotment.
    assert container_cpu_util(0.03, str(tmp_path), host_cores=64) \
        == pytest.approx(0.96)


def test_cgroup_v2_unlimited(tmp_path):
    _write(str(tmp_path), "cpu.max", "max 100000\n")
    assert cgroup_cpu_cores(str(tmp_path), host_cores=16) == 16.0
    assert container_cpu_util(0.5, str(tmp_path), host_cores=16) == 0.5


def test_cgroup_v1_quota(tmp_path):
    _write(str(tmp_path), "cpu/cpu.cfs_quota_us", "400000")
    _write(str(tmp_path), "cpu/cpu.cfs_period_us", "100000")
    assert cgroup_cpu_cores(str(tmp_path), host_cores=32) == 4.0


def test_cgroup_absent_falls_back_to_host(tmp_path):
    assert cgroup_cpu_cores(str(tmp_path / "nope"), host_cores=8) == 8.0


# ---- registry + agent ------------------------------------------------------

def _registry(broker_id, topics=("t0",), cpu=0.5, bytes_in=100.0):
    reg = BrokerMetricsRegistry(broker_id)
    reg.set_cpu_util(cpu)
    for t in topics:
        reg.set_topic_rate(t, bytes_in, 2 * bytes_in)
    reg.set_replication_bytes_in(10.0)
    return reg


def test_agent_reports_registry_snapshot(tmp_path):
    reg = _registry(7, topics=("a", "b"))
    reg.set_partition_size("a", 0, 5000.0)
    transport = InMemoryMetricsTransport()
    agent = MetricsReporterAgent(reg, transport, interval_s=3600,
                                 cgroup_root=str(tmp_path / "none"))
    n = agent.report_once(time_ms=1000)
    records = [deserialize(b) for b in transport.poll(0, 2000)]
    assert len(records) == n
    by_type = {}
    for m in records:
        by_type.setdefault(m.raw_type, []).append(m)
    assert by_type[R.ALL_TOPIC_BYTES_IN][0].value == pytest.approx(200.0)
    assert len(by_type[R.TOPIC_BYTES_IN]) == 2
    assert by_type[R.PARTITION_SIZE][0].partition == 0


def test_agent_adjusts_cpu_for_container(tmp_path):
    _write(str(tmp_path), "cpu.max", "100000 100000\n")  # 1 core
    host = os.cpu_count() or 1
    reg = _registry(1, cpu=0.5 / host)  # half of one host core
    transport = InMemoryMetricsTransport()
    agent = MetricsReporterAgent(reg, transport, cgroup_root=str(tmp_path))
    agent.report_once(time_ms=1000)
    cpu = [m for m in map(deserialize, transport.poll(0, 2000))
           if m.raw_type is R.BROKER_CPU_UTIL]
    assert cpu[0].value == pytest.approx(0.5)


def test_agent_loop_runs_on_interval():
    reg = _registry(0)
    transport = InMemoryMetricsTransport()
    agent = MetricsReporterAgent(reg, transport, interval_s=0.01)
    agent.start()
    deadline = time.time() + 5.0
    while agent.reports < 3 and time.time() < deadline:
        time.sleep(0.01)
    agent.stop()
    assert agent.reports >= 3


def test_agent_auto_creates_topic_when_transport_supports_it():
    class TopicTransport(InMemoryMetricsTransport):
        def __init__(self):
            super().__init__()
            self.created = 0

        def ensure_topic(self):
            self.created += 1

    transport = TopicTransport()
    agent = MetricsReporterAgent(_registry(0), transport, interval_s=3600)
    agent.start()
    agent.stop()
    assert transport.created == 1


# ---- end to end: agent → transport → sampler → aggregator → model ----------

def test_end_to_end_agent_to_cluster_model(tmp_path):
    brokers = (0, 1, 2)
    partitions = {}
    for t in range(2):
        topic = f"t{t}"
        for p in range(3):
            leader = brokers[(t + p) % 3]
            reps = (leader, brokers[(t + p + 1) % 3])
            partitions[(topic, p)] = PartitionState(topic, p, reps, leader,
                                                    isr=reps)

    # One registry + agent per broker, all feeding one transport.
    transport = InMemoryMetricsTransport()
    agents = []
    for b in brokers:
        led_topics = {t for (t, _p), st in partitions.items()
                      if st.leader == b}
        reg = _registry(b, topics=tuple(sorted(led_topics)))
        for (topic, p), st in partitions.items():
            if st.leader == b:
                reg.set_partition_size(topic, p, 5000.0)
        agents.append(MetricsReporterAgent(
            reg, transport, interval_s=3600,
            cgroup_root=str(tmp_path / "none")))

    backend = InMemoryAdminBackend(partitions.values())
    cfg = CruiseControlConfig({"partition.metrics.window.ms": 1000,
                               "num.partition.metrics.windows": 3,
                               "min.valid.partition.ratio": 0.0})
    monitor = LoadMonitor(
        cfg, backend,
        samplers=[CruiseControlMetricsReporterSampler(transport)])
    for k in range(1, 4):
        for agent in agents:
            agent.report_once(time_ms=k * 1000 - 500)
        monitor.task_runner.run_sampling_once(end_ms=k * 1000)

    state, meta = monitor.cluster_model(
        ModelCompletenessRequirements(min_valid_windows=1,
                                      min_monitored_partitions_percentage=0.5))
    assert state.num_brokers == 3
    assert int(state.partition_mask.sum()) == len(partitions)
    loads = np.asarray(broker_load(state))
    # Each broker leads one partition per topic (100 B/s topic rate split
    # across 3 partitions... each leads 2 partitions of different topics):
    # leader NW_IN 100·(2/3)? — just require uniform positive load.
    assert (loads[:, int(Resource.NW_IN)] > 0).all()
    assert np.allclose(loads[:, int(Resource.NW_IN)],
                       loads[0, int(Resource.NW_IN)], rtol=0.05)


def test_system_metrics_registry_psutil_bridge(tmp_path):
    """SystemMetricsRegistry: real host CPU + NIC rates + log-dir partition
    sizes (the deployer-side registry bridge)."""
    from cruise_control_tpu.metricdef.raw_metric_type import RawMetricType as R
    from cruise_control_tpu.reporter.agent import SystemMetricsRegistry

    logdir = tmp_path / "kafka-logs"
    pdir = logdir / "t7-3"
    pdir.mkdir(parents=True)
    (pdir / "00000000.log").write_bytes(b"x" * 2048)
    (logdir / "not-a-partition").mkdir()

    reg = SystemMetricsRegistry(broker_id=9, log_dirs=[str(logdir)])
    first = reg.snapshot(time_ms=1_000)
    types = {m.raw_type for m in first}
    assert R.BROKER_CPU_UTIL in types
    sizes = [m for m in first if m.raw_type is R.PARTITION_SIZE]
    assert len(sizes) == 1
    assert sizes[0].topic == "t7" and sizes[0].partition == 3
    assert sizes[0].value == 2048.0
    # Second snapshot: NIC deltas appear as ALL_TOPIC byte rates.
    second = reg.snapshot(time_ms=2_000)
    types2 = {m.raw_type for m in second}
    assert {R.ALL_TOPIC_BYTES_IN, R.ALL_TOPIC_BYTES_OUT} <= types2


def test_columnar_deserialize_matches_scalar():
    """deserialize_columns over a concatenated buffer must reproduce every
    field of the per-record deserialize."""
    import numpy as np

    from cruise_control_tpu.metricdef.raw_metric_type import RawMetricType as R
    from cruise_control_tpu.reporter.metrics import (
        broker_metric, deserialize, deserialize_columns, partition_metric,
        serialize, topic_metric,
    )

    rng = np.random.default_rng(7)
    metrics = []
    for i in range(500):
        kind = i % 3
        if kind == 0:
            metrics.append(broker_metric(R.BROKER_CPU_UTIL, 1000 + i, i % 9,
                                         float(rng.uniform(0, 1))))
        elif kind == 1:
            metrics.append(topic_metric(R.TOPIC_BYTES_IN, 1000 + i, i % 9,
                                        f"topic-{i % 13}",
                                        float(rng.uniform(0, 1e6))))
        else:
            metrics.append(partition_metric(R.PARTITION_SIZE, 1000 + i, i % 9,
                                            f"topic-{i % 13}", i % 40,
                                            float(rng.uniform(0, 1e7))))
    payloads = [serialize(m) for m in metrics]
    data = b"".join(payloads)
    spans, off = [], 0
    for p in payloads:
        spans.append((off, len(p)))
        off += len(p)
    cols = deserialize_columns(data, np.asarray(spans, dtype=np.int64))
    assert len(cols) == len(metrics)
    for i, m in enumerate(metrics):
        ref = deserialize(payloads[i])
        assert R(int(cols.raw_id[i])) is ref.raw_type
        assert int(cols.time_ms[i]) == ref.time_ms
        assert int(cols.broker[i]) == ref.broker_id
        assert float(cols.value[i]) == ref.value
        topic = cols.topics[cols.topic_id[i]] if cols.topic_id[i] >= 0 else None
        assert topic == ref.topic
        part = int(cols.partition[i])
        assert part == (ref.partition if ref.partition >= 0 else -1)


def test_columnar_broker_loads_match_scalar_grouping():
    import numpy as np

    from cruise_control_tpu.metricdef.raw_metric_type import RawMetricType as R
    from cruise_control_tpu.monitor.sampling.holder import (
        broker_loads_from_columns, group_by_broker,
    )
    from cruise_control_tpu.reporter.metrics import (
        broker_metric, deserialize_columns, partition_metric, serialize,
        topic_metric,
    )

    rng = np.random.default_rng(3)
    metrics = []
    for i in range(600):
        b = int(rng.integers(0, 5))
        kind = int(rng.integers(0, 3))
        if kind == 0:
            metrics.append(broker_metric(R.ALL_TOPIC_BYTES_IN, 1000, b,
                                         float(rng.uniform(0, 100))))
        elif kind == 1:
            metrics.append(topic_metric(R.TOPIC_BYTES_OUT, 1000, b,
                                        f"t{int(rng.integers(0, 4))}",
                                        float(rng.uniform(0, 100))))
        else:
            # duplicates on purpose: last-observation-wins for sizes
            metrics.append(partition_metric(R.PARTITION_SIZE, 1000, b,
                                            f"t{int(rng.integers(0, 4))}",
                                            int(rng.integers(0, 6)),
                                            float(rng.uniform(0, 100))))
    payloads = [serialize(m) for m in metrics]
    data = b"".join(payloads)
    spans, off = [], 0
    for p in payloads:
        spans.append((off, len(p)))
        off += len(p)
    cols = deserialize_columns(data, np.asarray(spans, dtype=np.int64))
    col_loads = broker_loads_from_columns(cols)
    ref_loads = group_by_broker(metrics)
    assert set(col_loads) == set(ref_loads)
    for b, ref in ref_loads.items():
        got = col_loads[b]
        # Derived views must agree (means of lists vs single-element mean).
        for raw in set(ref.broker_metrics):
            assert got.broker_metric(raw) == pytest.approx(
                ref.broker_metric(raw))
        for (t, raw) in set(ref.topic_metrics):
            assert got.topic_metric(t, raw) == pytest.approx(
                ref.topic_metric(t, raw))
        assert got.partition_sizes == ref.partition_sizes
