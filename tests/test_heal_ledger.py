"""Heal-ledger tests: journal mechanics on an injected clock, the
notifier escalation paths' documented terminal phases, the
observation-never-changes-behavior parity pin (ledger on/off ⇒
byte-identical proposals + final assignment at two bucket shapes), the
twin cross-validation (ledger heal durations == ScenarioScore
time-to-heal ticks on the sim clock, score JSON unchanged), and the
GET /heals endpoint serving a complete correlated chain whose solver
pass ids resolve in GET /solver."""

from __future__ import annotations

import dataclasses
import json
import pathlib
import sys

import pytest

ROOT = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT))

from cruise_control_tpu.utils.heal_ledger import (  # noqa: E402
    NO_HEAL, HealLedger, current_heal, heal_scope,
)


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


# ---------------------------------------------------------------------------
# Journal mechanics

def test_chain_lifecycle_on_injected_clock():
    clk = FakeClock()
    led = HealLedger(clock=clk)
    h = led.open("BROKER_FAILURE", "a-1", signature=(5,))
    clk.t += 2.0
    h.phase("verdict", action="FIX")
    clk.t += 3.0
    h.phase("fix_started")
    clk.t += 5.0
    h.resolve("cleared")
    (c,) = led.chains()
    assert c["outcome"] == "cleared"
    assert c["healSeconds"] == 10.0
    assert c["timeToStartFixMs"] == 5000
    phases = [p["phase"] for p in c["phases"]]
    assert phases == ["detected", "verdict", "fix_started", "cleared"]
    assert [p["durationMs"] for p in c["phases"]] == [0, 2000, 3000, 5000]
    assert led.heal_durations_s("BROKER_FAILURE") == [10.0]
    assert led.mean_time_to_start_fix_ms() == 5000.0


def test_redetection_aliases_onto_open_chain():
    led = HealLedger(clock=FakeClock())
    h1 = led.open("BROKER_FAILURE", "a-1", signature=(5,))
    h2 = led.open("BROKER_FAILURE", "a-2", signature=(5,))
    assert h2.chain_id == h1.chain_id
    assert led.handle_for("a-2").chain_id == h1.chain_id
    assert len(led.chains()) == 1
    assert [p["phase"] for p in led.chains()[0]["phases"]] \
        == ["detected", "redetected"]
    # A different signature is a different incident.
    h3 = led.open("BROKER_FAILURE", "a-3", signature=(7,))
    assert h3.chain_id != h1.chain_id
    # Resolved chains never absorb re-detections: same condition again
    # later = a new heal.
    h1.resolve("cleared")
    h4 = led.open("BROKER_FAILURE", "a-4", signature=(5,))
    assert h4.chain_id not in (h1.chain_id, h3.chain_id)


def test_ring_bound_evicts_open_chains_loudly():
    led = HealLedger(max_chains=2, clock=FakeClock())
    h1 = led.open("GOAL_VIOLATION", "a-1", ("g1",))
    led.open("GOAL_VIOLATION", "a-2", ("g2",))
    led.open("GOAL_VIOLATION", "a-3", ("g3",))
    chains = led.chains()
    assert len(chains) == 2
    # The evicted chain's handle goes dead (no resurrection) and its
    # alias is pruned.
    assert led.handle_for("a-1") is NO_HEAL
    h1.phase("late")  # no-op on an evicted chain, never raises
    h1.resolve("cleared")
    assert {c["anomalyId"] for c in chains} == {"a-2", "a-3"}
    # An open eviction counts as resolved (outcome=evicted), so the
    # opened/resolved counters always reconcile.
    assert led.chains_opened == 3
    assert led.chains_resolved == 1
    assert led.open_count() == 2


def test_max_phases_counts_drops():
    led = HealLedger(max_phases=4, clock=FakeClock())
    h = led.open("BROKER_FAILURE", "a-1")
    for i in range(6):
        h.phase(f"p{i}")
    (c,) = led.chains()
    assert len(c["phases"]) == 4
    assert c["droppedPhases"] == 3


def test_disabled_ledger_is_shared_noop():
    led = HealLedger(enabled=False)
    h = led.open("BROKER_FAILURE", "a-1")
    assert h is NO_HEAL and not h.recording
    h.phase("anything")
    h.resolve("cleared")
    assert led.handle_for("a-1") is NO_HEAL
    assert led.chains() == [] and led.open_count() == 0
    assert led.clear_types(("BROKER_FAILURE",)) == 0


def test_ambient_scope_and_null_default():
    assert current_heal() is NO_HEAL
    led = HealLedger(clock=FakeClock())
    h = led.open("DISK_FAILURE", "a-1")
    with heal_scope(h):
        assert current_heal() is h
        with heal_scope(None):
            assert current_heal() is NO_HEAL
        assert current_heal() is h
    assert current_heal() is NO_HEAL


def test_observe_health_clears_health_types_only():
    led = HealLedger(clock=FakeClock())
    led.open("BROKER_FAILURE", "a-1", (5,))
    led.open("GOAL_VIOLATION", "a-2", ("g",))
    assert led.observe_health(False) == 0
    assert led.observe_health(True) == 1
    by_id = {c["anomalyId"]: c for c in led.chains()}
    assert by_id["a-1"]["outcome"] == "cleared"
    assert by_id["a-1"]["phases"][-1]["via"] == "health_observation"
    assert by_id["a-2"]["outcome"] is None
    assert led.clear_types(("GOAL_VIOLATION",)) == 1
    assert led.open_count() == 0


def test_stale_stamps_coalesce_and_never_exhaust_phase_budget():
    led = HealLedger(max_phases=8, clock=FakeClock())
    h = led.open("BROKER_FAILURE", "a-1", (5,))
    for i in range(50):   # a dashboard hammering a broken proposals path
        led.note_stale(1.0 + i)
    (c,) = led.chains()
    stale = [p for p in c["phases"] if p["phase"] == "stale_serving"]
    assert len(stale) == 1
    assert stale[0]["staleServed"] == 50
    assert stale[0]["stalenessS"] == 50.0
    # The real lifecycle still fits: phases interleaved with stale
    # windows append a new coalesced stamp, not 50 of them.
    h.phase("fix_started")
    led.note_stale(99.0)
    h.resolve("cleared")
    (c,) = led.chains()
    assert [p["phase"] for p in c["phases"]] == [
        "detected", "stale_serving", "fix_started", "stale_serving",
        "cleared"]
    assert c.get("droppedPhases") is None


def test_heals_open_gauge_zeroes_after_type_vanishes():
    from cruise_control_tpu.utils.sensors import SENSORS
    led = HealLedger(max_chains=1, clock=FakeClock())
    led.open("BROKER_FAILURE", "a-1", (5,))
    # Churn of another type evicts every BROKER_FAILURE chain from the
    # ring; the gauge must drop to 0, not freeze at 1.
    led.open("GOAL_VIOLATION", "g-1", ("g",))
    text = SENSORS.render()
    assert 'heals_open{type="BROKER_FAILURE"} 0.0' in text
    assert 'heals_open{type="GOAL_VIOLATION"} 1.0' in text


def test_soft_terminal_keeps_chain_open_after_real_fix():
    """A re-detected incident's redundant second fix attempt failing to
    start must not close a chain whose first fix is already executing
    (the per-tick-detection twin hits exactly this)."""
    led = HealLedger(clock=FakeClock())
    h = led.open("BROKER_FAILURE", "a-1", (5,))
    h.phase("fix_started")
    h.phase("execution_started")
    h.phase("fix_started")            # the redundant re-attempt
    h.resolve("fix_failed_to_start", own_fix_started=True)
    (c,) = led.chains()
    assert c["outcome"] is None       # still open
    assert c["phases"][-1]["phase"] == "fix_failed_to_start_attempt"
    assert "own_fix_started" not in c["phases"][-1]  # bookkeeping popped
    h.resolve("cleared")
    assert led.chains()[0]["outcome"] == "cleared"
    # An early-out failure (no facade / model not ready) records NO
    # fix_started of its own — it must not close a chain whose real
    # fix already started either.
    h1b = led.open("BROKER_FAILURE", "c-1", (6,))
    h1b.phase("fix_started")
    h1b.resolve("fix_failed_to_start", reason="model not ready")
    assert led.chains()[0]["outcome"] is None
    assert led.chains()[0]["phases"][-1]["phase"] \
        == "fix_failed_to_start_attempt"
    h1b.resolve("cleared")
    # But a chain whose ONLY attempt failed terminates.
    h2 = led.open("BROKER_FAILURE", "b-1", (7,))
    h2.phase("fix_started")
    h2.resolve("fix_failed_to_start", own_fix_started=True)
    assert led.chains()[0]["outcome"] == "fix_failed_to_start"
    # ...and an early-out with no fix ever started terminates too.
    h3 = led.open("BROKER_FAILURE", "d-1", (8,))
    h3.resolve("fix_failed_to_start", reason="no facade")
    assert led.chains()[0]["outcome"] == "fix_failed_to_start"


# ---------------------------------------------------------------------------
# Escalation paths through the real manager (satellite: each path leaves
# its documented terminal phase)

def _manager(notifier=None, facade=None, clock=None):
    from cruise_control_tpu.config.cruise_control_config import (
        CruiseControlConfig,
    )
    from cruise_control_tpu.detector.manager import AnomalyDetectorManager
    cfg = CruiseControlConfig({
        "self.healing.enabled": True,
        "broker.failure.alert.threshold.ms": 0,
        "broker.failure.self.healing.threshold.ms": 1000,
    })
    return AnomalyDetectorManager(cfg, notifier=notifier, facade=facade,
                                  clock=clock)


class _Facade:
    def __init__(self, fix_ok=True, valid=True):
        self.fix_ok = fix_ok
        self.valid = valid
        self.fixes = 0

    def ready_for_self_healing(self):
        return True


class _Anomaly:
    """Minimal anomaly double (duck-typed like the manager's users)."""

    def __init__(self, aid="x-1", fix_ok=True, valid=True):
        from cruise_control_tpu.detector.anomaly import AnomalyType
        self.anomaly_type = AnomalyType.BROKER_FAILURE
        self.anomaly_id = aid
        self.detection_time_ms = 0
        self.failed_brokers = {5: 0}
        self._fix_ok = fix_ok
        self._valid = valid

    def reasons(self):
        return ["test"]

    def still_valid(self, facade):
        return self._valid

    def fix(self, facade):
        if isinstance(self._fix_ok, Exception):
            raise self._fix_ok
        facade.fixes += 1
        return self._fix_ok


def _fix_notifier():
    from cruise_control_tpu.detector.notifier import (
        AnomalyNotificationResult, AnomalyNotifier,
    )

    class N(AnomalyNotifier):
        def on_anomaly(self, anomaly):
            return AnomalyNotificationResult.fix()
    return N()


def _verdict_notifier(result):
    from cruise_control_tpu.detector.notifier import AnomalyNotifier

    class N(AnomalyNotifier):
        def on_anomaly(self, anomaly):
            return result
    return N()


def test_ignore_verdict_terminal():
    from cruise_control_tpu.detector.notifier import (
        AnomalyNotificationResult,
    )
    mgr = _manager(_verdict_notifier(AnomalyNotificationResult.ignore()))
    a = _Anomaly()
    mgr.report(a)
    mgr.handle_anomaly(a)
    (c,) = mgr.heal_ledger.chains()
    assert c["outcome"] == "ignored"
    assert c["phases"][-1]["verdict"] == "IGNORE"


def test_delayed_check_then_recheck_promotion_to_fix():
    clk = FakeClock(0.0)
    facade = _Facade()
    from cruise_control_tpu.detector.notifier import SelfHealingNotifier
    from cruise_control_tpu.config.cruise_control_config import (
        CruiseControlConfig,
    )
    cfg = CruiseControlConfig({
        "self.healing.enabled": True,
        "broker.failure.alert.threshold.ms": 0,
        "broker.failure.self.healing.threshold.ms": 1000,
    })
    notifier = SelfHealingNotifier(cfg, now_ms=lambda: int(clk() * 1000))
    mgr = _manager(notifier, facade=facade, clock=clk)
    a = _Anomaly()
    mgr.report(a)
    assert mgr.drain_anomalies() == 1   # verdict: CHECK, recheck parked
    (c,) = mgr.heal_ledger.chains()
    assert [p["phase"] for p in c["phases"]] \
        == ["detected", "alerted", "verdict"]
    assert c["phases"][-1]["action"] == "CHECK"
    # Past the self-healing threshold the recheck promotes to FIX.
    clk.t = 2.0
    assert mgr.drain_anomalies() == 1
    (c,) = mgr.heal_ledger.chains()
    phases = [p["phase"] for p in c["phases"]]
    assert "recheck_promoted" in phases and "fix_started" in phases
    assert facade.fixes == 1
    assert c["outcome"] is None   # open until the violation re-checks clear
    # The detector all-clear seam is the production re-check.
    mgr.heal_ledger.clear_types(("BROKER_FAILURE",))
    (c,) = mgr.heal_ledger.chains()
    assert c["outcome"] == "cleared"
    assert c["phases"][-1]["via"] == "detector_all_clear"


def test_recheck_self_cleared_terminal():
    clk = FakeClock(0.0)
    from cruise_control_tpu.detector.notifier import (
        AnomalyNotificationResult,
    )
    mgr = _manager(_verdict_notifier(AnomalyNotificationResult.check(500)),
                   facade=_Facade(), clock=clk)
    a = _Anomaly(valid=False)
    mgr.report(a)
    mgr.handle_anomaly(a)
    clk.t = 2.0
    mgr.drain_anomalies()
    (c,) = mgr.heal_ledger.chains()
    assert c["outcome"] == "self_cleared"


def test_breaker_skipped_fix_terminal():
    from cruise_control_tpu.utils.resilience import BreakerOpenError
    mgr = _manager(_fix_notifier(), facade=_Facade())

    def skipping_runner(fn):
        raise BreakerOpenError("c1", 12.0)

    mgr.fix_runner = skipping_runner
    a = _Anomaly()
    mgr.report(a)
    assert mgr.handle_anomaly(a) == "FIX_FAILED_TO_START"
    (c,) = mgr.heal_ledger.chains()
    assert c["outcome"] == "breaker_skipped"


def test_fix_crash_terminal_and_started_counter_by_type():
    mgr = _manager(_fix_notifier(), facade=_Facade())
    bad = _Anomaly("bad", fix_ok=RuntimeError("boom"))
    mgr.report(bad)
    mgr.handle_anomaly(bad)
    assert mgr.heal_ledger.chains()[0]["outcome"] == "fix_failed_to_start"
    good = _Anomaly("good")
    good.failed_brokers = {9: 0}  # distinct signature → its own chain
    mgr.report(good)
    assert mgr.handle_anomaly(good) == "FIX_STARTED"
    st = mgr.state()
    assert st["metrics"]["numSelfHealingStarted"] == 1
    assert st["metrics"]["selfHealingStartedByType"] \
        == {"BROKER_FAILURE": 1}
    assert st["meanTimeToStartFixMs"] is not None
    assert any(r["type"] == "BROKER_FAILURE" for r in st["recentHeals"])


def test_executor_dead_letter_terminal():
    """An execution whose submissions dead-letter resolves the
    correlated heal as dead_lettered (the documented terminal)."""
    from cruise_control_tpu.analyzer.proposals import ExecutionProposal
    from cruise_control_tpu.executor.admin import (
        InMemoryAdminBackend, PartitionState,
    )
    from cruise_control_tpu.executor.executor import Executor
    from cruise_control_tpu.utils.resilience import RetryPolicy

    parts = [PartitionState("t0", 0, (0, 1), 0, isr=(0, 1))]
    backend = InMemoryAdminBackend(parts)

    class FailingBackend:
        def __getattr__(self, name):
            return getattr(backend, name)

        def alter_partition_reassignments(self, targets):
            raise TimeoutError("control plane unreachable")

    led = HealLedger(clock=FakeClock())
    h = led.open("BROKER_FAILURE", "a-1", (1,))
    h.phase("fix_started")
    ex = Executor(FailingBackend(), synchronous=True,
                  progress_check_interval_s=0.0, adjuster_enabled=False,
                  retry_policy=RetryPolicy(max_attempts=1,
                                           base_backoff_s=0.0,
                                           max_backoff_s=0.0),
                  dead_letter_attempts=1)
    proposal = ExecutionProposal(topic="t0", partition=0, old_leader=0,
                                 old_replicas=(0, 1), new_replicas=(0, 2),
                                 new_leader=0)
    with heal_scope(h):
        ex.execute_proposals([proposal], uuid="heal-fix")
    (c,) = led.chains()
    assert c["outcome"] == "dead_lettered"
    phases = [p["phase"] for p in c["phases"]]
    assert "execution_started" in phases and "dead_letter" in phases
    assert "execution_finished" in phases
    # The executor forgets the handle afterwards: an uncorrelated
    # execution records nothing more on the chain.
    assert ex._heal is NO_HEAL


def test_scheduler_queue_wait_and_breaker_skip_attribution():
    from cruise_control_tpu.fleet.scheduler import FleetScheduler, JobKind
    from cruise_control_tpu.utils.resilience import (
        BreakerOpenError, CircuitBreaker,
    )

    led = HealLedger(clock=FakeClock())
    h = led.open("BROKER_FAILURE", "a-1", (5,))
    clk = FakeClock(0.0)
    sched = FleetScheduler(starvation_bound_s=100.0, clock=clk)
    with heal_scope(h):
        fut = sched.submit("c1", JobKind.SELF_HEALING, lambda: "done")
    clk.t = 4.0
    assert sched.run_pending() == 1
    assert fut.result() == "done"
    (c,) = led.chains()
    queued = [p for p in c["phases"] if p["phase"] == "solver_queued"]
    assert queued and queued[0]["kind"] == "SELF_HEALING"
    assert queued[0]["waitS"] == 4.0

    # Open breaker: the queued fix resolves breaker_skipped.
    h2 = led.open("BROKER_FAILURE", "b-1", (7,))
    breaker = CircuitBreaker(failure_threshold=1, recovery_s=1000.0,
                             clock=clk)
    breaker.record_failure("c2")
    sched2 = FleetScheduler(starvation_bound_s=100.0, clock=clk,
                            breaker=breaker)
    with heal_scope(h2):
        fut2 = sched2.submit("c2", JobKind.SELF_HEALING, lambda: "done")
    sched2.run_pending()
    with pytest.raises(BreakerOpenError):
        fut2.result(timeout=1)
    assert led.chains()[0]["outcome"] == "breaker_skipped"


# ---------------------------------------------------------------------------
# The twin: parity pin, cross-validation, and the served chain

def _twin(ticks=28, overrides=None):
    from cruise_control_tpu.testing.simulator import (
        CANONICAL_SCENARIOS, ClusterSimulator,
    )
    spec = dataclasses.replace(CANONICAL_SCENARIOS["broker_loss_drift"],
                               ticks=ticks)
    # Per-tick detection: the detector sees the kill the tick it lands,
    # so the ledger's detected anchor equals the score's injected tick —
    # the precondition for exact cross-validation (with the canonical
    # 10-tick cadence the score deliberately charges detection latency
    # the ledger cannot see).
    return ClusterSimulator(spec, seed=0, config_overrides={
        "anomaly.detection.interval.ms": 60_000, **(overrides or {})})


@pytest.fixture(scope="module")
def healed_twin():
    from cruise_control_tpu.utils.flight_recorder import FLIGHT
    FLIGHT.configure(enabled=True)
    sim = _twin()
    result = sim.run()
    return sim, result


def test_twin_cross_validation_equals_scenario_score(healed_twin):
    """The instrument vs the ground truth: every injected broker fault's
    ScenarioScore time-to-heal (ticks) equals the ledger chain's heal
    duration on the sim clock, exactly."""
    sim, result = healed_twin
    tick_s = sim.spec.tick_s
    events = [h for h in result.score.heal_events if h.kind == "kill_broker"]
    assert events and all(h.ticks_to_heal is not None for h in events)
    chains = sim.cc.heal_ledger.chains(anomaly_type="BROKER_FAILURE")
    cleared = [c for c in chains if c["outcome"] == "cleared"]
    assert cleared
    for ev in events:
        broker = None
        for e in sim.events:
            if e.kind == "kill_broker" and e.tick == ev.injected_tick:
                broker = int(e.params["broker"])
        covering = [c for c in cleared if broker in c["signature"]]
        assert covering, f"no ledger chain covers broker {broker}"
        assert covering[0]["healSeconds"] == ev.ticks_to_heal * tick_s


def test_twin_multi_az_cross_validation():
    sim_cls = _twin  # reuse the override recipe
    from cruise_control_tpu.testing.simulator import (
        CANONICAL_SCENARIOS, ClusterSimulator,
    )
    spec = dataclasses.replace(CANONICAL_SCENARIOS["multi_az_failure"],
                               ticks=32)
    sim = ClusterSimulator(spec, seed=0, config_overrides={
        "anomaly.detection.interval.ms": 60_000})
    result = sim.run()
    del sim_cls
    events = [h for h in result.score.heal_events
              if h.kind == "kill_broker" and h.ticks_to_heal is not None]
    assert events
    cleared = [c for c in sim.cc.heal_ledger.chains(
        anomaly_type="BROKER_FAILURE") if c["outcome"] == "cleared"]
    assert cleared
    for ev in events:
        durations = {c["healSeconds"] for c in cleared}
        assert ev.ticks_to_heal * spec.tick_s in durations


@pytest.mark.parametrize("bucket", [128, 256])
def test_ledger_parity_byte_identical(bucket):
    """Ledger on vs off: byte-identical final assignment, score JSON,
    and post-run proposals at two padded bucket shapes (observation
    never changes behavior — the flight-recorder contract family)."""
    outs = []
    for enabled in (True, False):
        sim = _twin(ticks=26, overrides={
            "solver.partition.bucket.size": bucket,
            "heal.ledger.enabled": enabled})
        result = sim.run()
        props = sim.cc.proposals(ignore_proposal_cache=True)
        outs.append((result.assignment_digest, result.score.to_json(),
                     [dataclasses.astuple(p) for p in props.proposals]))
        if enabled:
            assert sim.cc.heal_ledger.chains(), \
                "enabled run must have journaled chains"
        else:
            assert sim.cc.heal_ledger.chains() == []
    on, off = outs
    assert on[0] == off[0], "final assignments diverged"
    assert on[1] == off[1], "score JSON diverged"
    assert on[2] == off[2], "proposals diverged"


def test_heals_endpoint_serves_complete_chain(healed_twin):
    """GET /heals returns the full detected→…→cleared chain for the
    self-healed broker failure, and its solver pass ids resolve in
    GET /solver (acceptance criterion)."""
    from cruise_control_tpu.api.server import CruiseControlApi
    sim, _result = healed_twin
    api = CruiseControlApi(sim.cc)
    try:
        status, body, _ = api.handle("GET", "/kafkacruisecontrol/heals",
                                     "anomaly_type=BROKER_FAILURE")
        assert status == 200, body
        assert body["healLedgerEnabled"] is True
        assert body["numChains"] >= 1
        assert body["meanTimeToStartFixMs"] is not None
        chains = [c for c in body["chains"] if c["outcome"] == "cleared"]
        assert chains
        c = chains[0]
        phases = [p["phase"] for p in c["phases"]]
        for expected in ("detected", "verdict", "fix_started",
                         "model_built", "solve_dispatched",
                         "solve_completed", "proposal_ready",
                         "execution_started", "execution_progress",
                         "execution_finished", "cleared"):
            assert expected in phases, f"missing phase {expected}: {phases}"
        # Causal ordering + per-phase durations.
        at = [p["atMs"] for p in c["phases"]]
        assert at == sorted(at)
        assert all("durationMs" in p for p in c["phases"])
        # The chain links the flight recorder: its pass ids resolve in
        # GET /solver.
        seqs = [p["passSeqs"] for p in c["phases"]
                if p["phase"] == "solve_completed" and p.get("passSeqs")]
        assert seqs, "solve_completed must carry flight pass ids"
        status, solver_body, _ = api.handle(
            "GET", "/kafkacruisecontrol/solver", "entries=64")
        assert status == 200
        recorded = {p["passSeq"] for p in solver_body["passes"]}
        assert set(seqs[0]) <= recorded, \
            f"pass ids {seqs[0]} not resolvable in /solver ({recorded})"
        # anomaly_type filter + entries bound + unknown-param 400.
        status, body2, _ = api.handle("GET", "/kafkacruisecontrol/heals",
                                     "entries=1")
        assert status == 200 and len(body2["chains"]) == 1
        status, _b, _ = api.handle("GET", "/kafkacruisecontrol/heals",
                                   "nope=1")
        assert status == 400
    finally:
        api.shutdown()


def test_state_substate_and_sensors(healed_twin):
    sim, _result = healed_twin
    st = sim.cc.state(substates=("anomaly_detector",))
    ad = st["AnomalyDetectorState"]
    assert ad["meanTimeToStartFixMs"] is not None
    assert ad["recentHeals"] and \
        any(r["outcome"] == "cleared" for r in ad["recentHeals"])
    assert ad["metrics"]["numSelfHealingStarted"] >= 1
    assert sum(ad["metrics"]["selfHealingStartedByType"].values()) \
        == ad["metrics"]["numSelfHealingStarted"]
    from cruise_control_tpu.utils.sensors import SENSORS
    text = SENSORS.render()
    assert "kafka_cruisecontrol_self_healing_started_total" in text
    assert "kafka_cruisecontrol_time_to_heal_seconds_bucket" in text
    assert "kafka_cruisecontrol_heal_phase_seconds_bucket" in text
    assert "kafka_cruisecontrol_heals_open" in text


def test_ledger_dump_json(healed_twin, tmp_path):
    sim, _result = healed_twin
    path = tmp_path / "heals.json"
    n = sim.cc.heal_ledger.dump_json(str(path))
    doc = json.loads(path.read_text())
    assert doc["numChains"] == n >= 1
    assert all("phases" in c for c in doc["chains"])
