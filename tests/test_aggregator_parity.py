"""Aggregator edge-case parity suite (VERDICT r3 weak #5 / next #7).

Ports the CASES — not the code — of the reference's
cruise-control-core RawMetricValuesTest.java and
MetricSampleAggregatorTest.java: window rollout at boundaries,
AVG_ADJACENT at the first/last stable window, the wrap-around cases
where rolling the ring turns an edge window into an interior one (and a
large leap evicts the neighbour instead), FORCED_INSUFFICIENT
thresholds, and ENTITY vs ENTITY_GROUP completeness option matrices.
Each test names the reference case it mirrors.
"""

import numpy as np
import pytest

from cruise_control_tpu.metricdef.metricdef import (
    MetricDef, ValueComputingStrategy as S,
)
from cruise_control_tpu.monitor.aggregator import (
    AggregationOptions, Extrapolation, Granularity, MetricSampleAggregator,
    NotEnoughValidWindowsError,
)

WINDOW_MS = 1000


def make_def():
    d = MetricDef()
    d.define("avg_m", S.AVG)
    d.define("max_m", S.MAX)
    d.define("latest_m", S.LATEST)
    return d


def agg(num_windows=6, min_samples=2, group_fn=None):
    return MetricSampleAggregator(num_windows, WINDOW_MS, min_samples,
                                  make_def(), group_fn=group_fn)


def fill(a, entity, window, n, base=10.0):
    for i in range(n):
        a.add_sample(entity, window * WINDOW_MS + i,
                     np.array([base + i, base + i, base + i]))


def cats_of(a, entity="e0", **opts):
    res = a.aggregate(AggregationOptions(min_valid_windows=1,
                                         include_invalid_entities=True,
                                         **opts))
    row = res.entities.index(entity)
    return res, res.extrapolations[row], res.values[row]


# ---- RawMetricValuesTest ports ------------------------------------------

def test_add_sample_to_evicted_window_is_dropped():
    """testAddSampleToEvictedWindows: a sample older than the retained
    range must be silently dropped, not resurrect an evicted window."""
    a = agg(num_windows=2)
    fill(a, "e0", 5, 2)
    assert not a.add_sample("e0", 1 * WINDOW_MS, np.zeros(3))
    assert a.num_samples() == 2


def test_add_sample_update_extrapolation_two_gaps():
    """testAddSampleUpdateExtrapolation: windows 3 and 5 empty; filling 4
    turns BOTH into valid AVG_ADJACENT windows (each now has two
    sufficient stable neighbours); before that they are invalid."""
    a = agg(num_windows=6, min_samples=1)
    for w in (2, 6):
        fill(a, "e0", w, 1)
    a.roll_to(7)  # stable range [2, 6]
    _res, cats, _vals = cats_of(a)
    # windows order: [2, 3, 4, 5, 6]
    assert cats[1] == Extrapolation.NO_VALID_EXTRAPOLATION  # 3
    assert cats[3] == Extrapolation.NO_VALID_EXTRAPOLATION  # 5
    fill(a, "e0", 4, 1)
    a.roll_to(7)
    _res, cats, _vals = cats_of(a)
    assert cats[0] == Extrapolation.NONE                    # 2
    assert cats[1] == Extrapolation.AVG_ADJACENT            # 3
    assert cats[2] == Extrapolation.NONE                    # 4
    assert cats[3] == Extrapolation.AVG_ADJACENT            # 5
    assert cats[4] == Extrapolation.NONE                    # 6


def test_aggregate_single_window_progression():
    """testAggregateSingleWindow: category walks NO_VALID →
    FORCED_INSUFFICIENT → AVG_AVAILABLE → NONE as samples accumulate in
    one window (min_samples=4, half-min=2)."""
    a = agg(num_windows=3, min_samples=4)
    a.roll_to(0)
    a.roll_to(1)  # window 0 stable, empty

    def window0_cat():
        cats, valid, _extra = a.store.classify()
        return (int(cats[0, 0]) if cats.size else None,
                bool(valid[0, 0]) if valid.size else None)

    fill(a, "e0", 1, 4)  # give the entity a row; window 1 stays current-ish
    a.roll_to(2)
    c, v = window0_cat()
    assert c == Extrapolation.NO_VALID_EXTRAPOLATION and not v

    fill(a, "e0", 0, 1)                      # 1 < half-min
    c, v = window0_cat()
    assert c == Extrapolation.FORCED_INSUFFICIENT and v

    fill(a, "e0", 0, 1, base=20.0)           # 2 == half-min
    c, v = window0_cat()
    assert c == Extrapolation.AVG_AVAILABLE and v

    fill(a, "e0", 0, 2, base=30.0)           # 4 == min
    c, v = window0_cat()
    assert c == Extrapolation.NONE and v


def test_adjacent_avg_value_blend_at_middle():
    """testExtrapolationAdjacentAvgAtMiddle: the AVG metric blends by
    sample count; MAX/LATEST blend by window count."""
    a = agg(num_windows=4, min_samples=2)
    fill(a, "e0", 0, 2, base=10.0)   # avg 10.5, max 11
    fill(a, "e0", 2, 2, base=12.0)   # avg 12.5, max 13
    fill(a, "e0", 3, 1)              # current
    a.roll_to(3)
    _res, cats, vals = cats_of(a)
    assert cats[1] == Extrapolation.AVG_ADJACENT
    # AVG: (10+11+12+13)/4 = 11.5 (the reference's 11.5 case).
    assert vals[0, 1] == pytest.approx(11.5)
    # MAX: (11 + 13)/2 = 12 (reference's 13.0 case shape: window-count blend).
    assert vals[1, 1] == pytest.approx(12.0)


def test_adjacent_avg_not_at_left_edge():
    """testExtrapolationAdjacentAvgAtLeftEdge: the FIRST stable window has
    no previous neighbour — an empty one is NO_VALID, never ADJACENT."""
    a = agg(num_windows=4, min_samples=2)
    a.roll_to(0)  # first tracked window = 0 (otherwise 0 is never retained)
    fill(a, "e0", 1, 2)
    fill(a, "e0", 2, 2)
    a.roll_to(3)  # stable [0, 2]; window 0 empty at the left edge
    _res, cats, vals = cats_of(a)
    assert cats[0] == Extrapolation.NO_VALID_EXTRAPOLATION
    assert vals[0, 0] == 0.0 and vals[1, 0] == 0.0


def test_adjacent_avg_not_at_right_edge():
    """testExtrapolationAdjacentAvgAtRightEdge: the LAST stable window has
    no next stable neighbour (the current window does not count)."""
    a = agg(num_windows=4, min_samples=2)
    fill(a, "e0", 0, 2)
    fill(a, "e0", 1, 2)
    fill(a, "e0", 3, 2)  # current window — NOT a stable neighbour
    a.roll_to(3)   # stable [0, 2]; window 2 empty at the right edge
    _res, cats, _vals = cats_of(a)
    assert cats[2] == Extrapolation.NO_VALID_EXTRAPOLATION


def test_edge_window_becomes_adjacent_when_ring_rolls():
    """testAdjacentAvgAtEdgeWhenNewWindowRollsOut: an empty window at the
    RIGHT edge becomes AVG_ADJACENT once the ring rolls one step forward
    and its next neighbour becomes stable."""
    a = agg(num_windows=6, min_samples=2)
    for w in (0, 1, 2, 4):
        fill(a, "e0", w, 2)
    a.roll_to(4)   # stable [0, 3]; 3 empty at right edge
    _res, cats, _vals = cats_of(a)
    assert cats[3] == Extrapolation.NO_VALID_EXTRAPOLATION
    a.roll_to(5)   # stable [0, 4]; 3 now interior with full 2 and 4
    _res, cats, _vals = cats_of(a)
    assert cats[3] == Extrapolation.AVG_ADJACENT


def test_edge_window_stays_invalid_after_large_leap():
    """testAdjacentAvgAtEdgeWhenNewWindowRollsOutWithLargeLeap: a far roll
    evicts the would-be neighbour, so the gap window never becomes
    ADJACENT — it is evicted or still neighbourless."""
    a = agg(num_windows=4, min_samples=2)
    for w in (0, 1, 2):
        fill(a, "e0", w, 2)
    a.roll_to(4)   # stable [0, 3]; 3 empty at edge
    a.roll_to(8)   # large leap: everything evicted/reset
    cats, valid, _ = a.store.classify()
    assert not valid[0].any()
    assert (cats[0] == int(Extrapolation.NO_VALID_EXTRAPOLATION)).all()


def test_forced_insufficient_thresholds_exact():
    """RawMetricValues.java:61 + :425-465 — the half-min boundary: with
    min_samples=5 (half-min=2), count 1 is FORCED_INSUFFICIENT, count 2
    is AVG_AVAILABLE, count 4 is still AVG_AVAILABLE, count 5 is NONE."""
    a = agg(num_windows=8, min_samples=5)
    for w, n in ((0, 5), (1, 1), (2, 5), (3, 2), (4, 4), (5, 5)):
        fill(a, "e0", w, n)
    a.roll_to(6)
    _res, cats, _vals = cats_of(a)
    # window 1 has full neighbours 0 and 2 -> ADJACENT takes precedence
    # over FORCED only when count < half-min AND neighbours qualify.
    assert cats[1] == Extrapolation.AVG_ADJACENT
    assert cats[3] == Extrapolation.AVG_AVAILABLE
    assert cats[4] == Extrapolation.AVG_AVAILABLE
    assert cats[0] == Extrapolation.NONE and cats[5] == Extrapolation.NONE

    # Without qualifying neighbours, count < half-min is FORCED.
    b = agg(num_windows=4, min_samples=5)
    fill(b, "e0", 0, 1)
    fill(b, "e0", 1, 1)
    b.roll_to(2)
    _res, cats_b, _vals = cats_of(b)
    assert cats_b[0] == Extrapolation.FORCED_INSUFFICIENT
    assert cats_b[1] == Extrapolation.FORCED_INSUFFICIENT


def test_max_allowed_extrapolations_gate():
    """RawMetricValues.isValid: an entity stays valid only while its
    extrapolated-window count is within max.allowed.extrapolations."""
    a = agg(num_windows=6, min_samples=4)
    for w in range(6):
        n = 2 if w in (1, 3) else 4   # two AVG_AVAILABLE windows
        fill(a, "e0", w, n)
    res = a.aggregate(AggregationOptions(
        min_valid_windows=1, max_allowed_extrapolations_per_entity=2))
    assert res.entity_valid[0]
    res = a.aggregate(AggregationOptions(
        min_valid_windows=1, max_allowed_extrapolations_per_entity=1,
        include_invalid_entities=True))
    assert not res.entity_valid[0]


# ---- MetricSampleAggregatorTest option-matrix ports ----------------------

def _two_topic_aggregator():
    """Fixture shaped like testAggregationOption1-7: topic t1 fully
    monitored, topic t2's second partition missing half its windows."""
    group_fn = lambda e: e.split("-")[0]
    a = agg(num_windows=4, min_samples=1, group_fn=group_fn)
    for w in range(5):
        fill(a, "t1-p0", w, 1)
        fill(a, "t1-p1", w, 1)
        fill(a, "t2-p0", w, 1)
        if w < 2:
            fill(a, "t2-p1", w, 1)
    a.roll_to(4)
    return a


def test_aggregation_option_entity_coverage_gate():
    """testAggregationOption1/2: a high min_valid_entity_ratio rejects
    windows where the sparse entity is invalid; lowering it admits them."""
    a = _two_topic_aggregator()
    with pytest.raises(NotEnoughValidWindowsError):
        a.aggregate(AggregationOptions(min_valid_entity_ratio=0.9,
                                       min_valid_windows=4))
    res = a.aggregate(AggregationOptions(min_valid_entity_ratio=0.5,
                                         min_valid_windows=4))
    assert len(res.window_indices) == 4


def test_aggregation_option_group_granularity_poisons_topic():
    """testAggregationOption3/4: under ENTITY_GROUP granularity the sparse
    partition invalidates its whole topic in the missing windows."""
    a = _two_topic_aggregator()
    comp_e = a.completeness(AggregationOptions(
        min_valid_windows=1, granularity=Granularity.ENTITY))
    comp_g = a.completeness(AggregationOptions(
        min_valid_windows=1, granularity=Granularity.ENTITY_GROUP))
    # Later windows: 3/4 entities valid; group mode drops both t2 members.
    assert comp_e.valid_entity_ratio_by_window[-1] == pytest.approx(3 / 4)
    assert comp_g.valid_entity_ratio_by_window[-1] == pytest.approx(2 / 4)
    assert comp_g.valid_entity_group_ratio_by_window[-1] == pytest.approx(1 / 2)


def test_aggregation_option_interested_entities_subset():
    """testAggregationOption5/6: completeness is computed over the
    interested-entity universe only."""
    a = _two_topic_aggregator()
    res = a.aggregate(AggregationOptions(
        min_valid_entity_ratio=1.0, min_valid_windows=4,
        interested_entities=("t1-p0", "t1-p1", "t2-p0")))
    assert len(res.window_indices) == 4
    assert sorted(res.entities) == ["t1-p0", "t1-p1", "t2-p0"]


def test_aggregation_option_include_invalid_entities():
    """testAggregationOption7: include_invalid_entities keeps the sparse
    entity's rows (zeros where invalid) instead of dropping them."""
    a = _two_topic_aggregator()
    res = a.aggregate(AggregationOptions(min_valid_windows=1,
                                         include_invalid_entities=True))
    row = res.entities.index("t2-p1")
    assert not res.entity_valid[row]
    assert res.values.shape[0] == 4
    res2 = a.aggregate(AggregationOptions(min_valid_windows=1))
    row2 = res2.entities.index("t2-p1")
    # Excluded: zeroed rows, alignment preserved.
    assert (res2.values[row2] == 0.0).all()


def test_window_range_restriction_start_end():
    """LOAD start/end params: only windows overlapping the range
    participate; an empty overlap raises NotEnoughValidWindows."""
    a = agg(num_windows=6, min_samples=1)
    for w in range(6):
        fill(a, "e0", w, 1)
    res = a.aggregate(AggregationOptions(
        min_valid_windows=1, start_ms=1 * WINDOW_MS, end_ms=3 * WINDOW_MS))
    assert res.window_indices == [1, 2, 3]
    with pytest.raises(NotEnoughValidWindowsError):
        a.aggregate(AggregationOptions(min_valid_windows=1,
                                       start_ms=50_000, end_ms=60_000))


def test_peek_current_window():
    """testPeekCurrentWindow: the in-fill window is readable without
    waiting for it to roll stable."""
    a = agg(num_windows=4, min_samples=1)
    for w in range(3):
        fill(a, "e0", w, 1)
    fill(a, "e0", 3, 2, base=40.0)  # current
    entities, vals = a.peek_current_window()
    assert entities == ["e0"]
    assert vals[0, 0] == pytest.approx(40.5)  # AVG of 40, 41


def test_large_interval_roll_resets_only_reentered_slots():
    """testAddSamplesWithLargeInterval: rolling far forward resets the ring
    slots that are re-entered; samples land in the fresh window."""
    a = agg(num_windows=3, min_samples=1)
    fill(a, "e0", 0, 2)
    fill(a, "e0", 100, 2)
    assert a.available_windows() == [97, 98, 99]
    assert a.num_samples() == 2  # only the current window's two samples
