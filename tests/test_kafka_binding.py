"""Kafka binding module: protocol conformance of the wire-backed bindings.

The bindings are self-contained (``kafka.wire`` implements the protocol —
no client library), so there is no import gating to test; what must hold
is that every binding implements the full surface of the protocol it
claims, with signatures that match the in-memory fakes (drift here breaks
swapping a fake for the real thing). The behavioral side lives in
``test_wire_integration.py`` against the embedded wire broker.
"""

import inspect

from cruise_control_tpu import kafka as kafka_binding
from cruise_control_tpu.executor.admin import AdminBackend, InMemoryAdminBackend
from cruise_control_tpu.monitor.sampling.sample_store import (
    FileSampleStore, NoopSampleStore, SampleStore,
)
from cruise_control_tpu.monitor.sampling.sampler import (
    InMemoryMetricsTransport, MetricsTransport,
)

import pytest


def _protocol_methods(proto) -> set[str]:
    return {name for name, m in vars(proto).items()
            if callable(m) and not name.startswith("_")}


def test_bindings_always_available():
    """Round-2 regression: the binding used to be import-gated on
    kafka-python, which this environment does not have — the live path was
    untestable dead code. The wire client removed the dependency."""
    assert kafka_binding.HAVE_KAFKA is True


@pytest.mark.parametrize("impl,proto", [
    (kafka_binding.KafkaAdminBackend, AdminBackend),
    (InMemoryAdminBackend, AdminBackend),
    (kafka_binding.KafkaMetricsTransport, MetricsTransport),
    (InMemoryMetricsTransport, MetricsTransport),
    (kafka_binding.KafkaSampleStore, SampleStore),
    (FileSampleStore, SampleStore),
    (NoopSampleStore, SampleStore),
])
def test_implements_full_protocol_surface(impl, proto):
    missing = _protocol_methods(proto) - {
        n for n, m in inspect.getmembers(impl, callable)
        if not n.startswith("_")}
    assert not missing, f"{impl.__name__} missing {sorted(missing)}"


def test_protocol_method_signatures_match_admin():
    """Positional arity of every AdminBackend method matches between the
    Kafka binding and the in-memory fake (drift here breaks swapping)."""
    for name in _protocol_methods(AdminBackend):
        sig_kafka = inspect.signature(
            getattr(kafka_binding.KafkaAdminBackend, name))
        sig_fake = inspect.signature(getattr(InMemoryAdminBackend, name))
        n_kafka = len([p for p in sig_kafka.parameters.values()
                       if p.default is inspect.Parameter.empty
                       and p.kind in (p.POSITIONAL_ONLY,
                                      p.POSITIONAL_OR_KEYWORD)])
        n_fake = len([p for p in sig_fake.parameters.values()
                      if p.default is inspect.Parameter.empty
                      and p.kind in (p.POSITIONAL_ONLY,
                                     p.POSITIONAL_OR_KEYWORD)])
        assert n_kafka == n_fake, name


def test_jbod_surface_present_on_live_backend():
    """VERDICT r2 missing #4: REMOVE_DISKS / rebalance_disk need
    replica_logdirs + alter_replica_logdirs on the real backend, not just
    the in-memory fake."""
    for method in ("describe_logdirs", "replica_logdirs",
                   "alter_replica_logdirs"):
        assert callable(getattr(kafka_binding.KafkaAdminBackend, method))
