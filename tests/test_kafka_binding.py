"""Kafka binding module: import gating + protocol conformance.

No Kafka client ships in this environment, so these tests pin down the
contract the binding must satisfy: the package imports cleanly, refuses
construction with actionable guidance, and implements every method of the
protocols it claims (AdminBackend / MetricsTransport / SampleStore) — the
same surface the in-memory fakes already satisfy and the executor/monitor
suites exercise. With kafka-python installed the constructors run instead
(skipif on HAVE_KAFKA flips the gating test).
"""

import inspect

import pytest

from cruise_control_tpu import kafka as kafka_binding
from cruise_control_tpu.executor.admin import AdminBackend, InMemoryAdminBackend
from cruise_control_tpu.monitor.sampling.sample_store import (
    FileSampleStore, NoopSampleStore, SampleStore,
)
from cruise_control_tpu.monitor.sampling.sampler import (
    InMemoryMetricsTransport, MetricsTransport,
)


def _protocol_methods(proto) -> set[str]:
    return {name for name, m in vars(proto).items()
            if callable(m) and not name.startswith("_")}


@pytest.mark.skipif(kafka_binding.HAVE_KAFKA,
                    reason="kafka-python installed: constructors work")
@pytest.mark.parametrize("ctor,args", [
    (kafka_binding.KafkaAdminBackend, ("localhost:9092",)),
    (kafka_binding.KafkaMetricsTransport, ("localhost:9092",)),
    (kafka_binding.KafkaSampleStore, ("localhost:9092",)),
])
def test_construction_is_gated_with_guidance(ctor, args):
    with pytest.raises(kafka_binding.KafkaClientUnavailableError) as err:
        ctor(*args)
    assert "kafka-python" in str(err.value)


@pytest.mark.parametrize("impl,proto", [
    (kafka_binding.KafkaAdminBackend, AdminBackend),
    (InMemoryAdminBackend, AdminBackend),
    (kafka_binding.KafkaMetricsTransport, MetricsTransport),
    (InMemoryMetricsTransport, MetricsTransport),
    (kafka_binding.KafkaSampleStore, SampleStore),
    (FileSampleStore, SampleStore),
    (NoopSampleStore, SampleStore),
])
def test_implements_full_protocol_surface(impl, proto):
    missing = _protocol_methods(proto) - {
        n for n, m in inspect.getmembers(impl, callable)
        if not n.startswith("_")}
    assert not missing, f"{impl.__name__} missing {sorted(missing)}"


def test_protocol_method_signatures_match_admin():
    """Positional arity of every AdminBackend method matches between the
    Kafka binding and the in-memory fake (drift here breaks swapping)."""
    for name in _protocol_methods(AdminBackend):
        sig_kafka = inspect.signature(
            getattr(kafka_binding.KafkaAdminBackend, name))
        sig_fake = inspect.signature(getattr(InMemoryAdminBackend, name))
        n_kafka = len([p for p in sig_kafka.parameters.values()
                       if p.default is inspect.Parameter.empty
                       and p.kind in (p.POSITIONAL_ONLY,
                                      p.POSITIONAL_OR_KEYWORD)])
        n_fake = len([p for p in sig_fake.parameters.values()
                      if p.default is inspect.Parameter.empty
                      and p.kind in (p.POSITIONAL_ONLY,
                                     p.POSITIONAL_OR_KEYWORD)])
        assert n_kafka == n_fake, name


@pytest.mark.skipif(not kafka_binding.HAVE_KAFKA,
                    reason="needs kafka-python + a live broker")
def test_live_admin_backend_round_trip():  # pragma: no cover
    """Executed only where kafka-python and a broker exist: the same
    executor flow the in-memory suite runs, against localhost."""
    backend = kafka_binding.KafkaAdminBackend("localhost:9092")
    assert backend.alive_brokers()
    backend.close()
