"""Round-22 adversarial scenario mining (redteam/): deterministic
frontier JSON, committed-frontier replay pins, the correlated fleet
cascade, forecaster blind-spot verdicts, the REDTEAM API surface +
``what_if=mined:`` replays, and off-means-off."""

from __future__ import annotations

import json
import pathlib
import zlib

import pytest

from cruise_control_tpu.futures.generator import (
    Perturbation, apply_perturbations, perturbed_future, sample_future,
)
from cruise_control_tpu.redteam import (
    Candidate, entry_spec, forecast_miss, frontier_json,
    global_factor_series, load_frontier, mine, replay_entry,
    save_frontier,
)
from cruise_control_tpu.redteam.blindspot import entry_blind_spot
from cruise_control_tpu.testing.simulator import (
    DriftSpec, ScenarioEvent, ScenarioSpec,
)
from cruise_control_tpu.utils.slo import scenario_margin

ROOT = pathlib.Path(__file__).resolve().parent.parent
COMMITTED_FRONTIER = ROOT / "fileStore" / "redteam_frontier.json"

#: One toy-scale sweep configuration shared by the determinism tests:
#: small enough for tier-1, deep enough to exercise mutation + frontier
#: trimming (generation 1 mutates generation 0's survivors).
SWEEP_KW = dict(population=3, generations=2, survivors=1,
                frontier_size=4, ticks=8, eval_budget=10, width=4)


@pytest.fixture(scope="module")
def shared_optimizer():
    """One GoalOptimizer for every mine() call in this module, so the
    decision-solve programs compile once (results are optimizer-
    independent — the parity pin in test_futures covers that)."""
    from cruise_control_tpu.analyzer.optimizer import GoalOptimizer
    from cruise_control_tpu.futures.evaluator import (
        FutureSpec, prepare_sampled,
    )
    f = perturbed_future("load_ramp", 1, 8, ())
    p = prepare_sampled(f, 8, fspec=FutureSpec("load_ramp", 1, 8))
    return GoalOptimizer(p.config)


# ---------------------------------------------------------------------------
# Perturbations (futures/generator.py)
# ---------------------------------------------------------------------------

def test_perturbations_are_pure_and_bounded():
    base = sample_future("cascading_failures", 5).replay_spec(24)
    amp = apply_perturbations(base, (Perturbation("drift_amplitude", 3.0),
                                     Perturbation("drift_amplitude", 3.0)))
    assert amp.drift.amplitude <= 0.95          # clamp
    phase = apply_perturbations(base, (Perturbation("drift_phase", 10.0),))
    assert phase.drift.phase_ticks == base.drift.phase_ticks + 10.0
    shifted = apply_perturbations(base, (Perturbation("event_timing", 6.0),))
    assert [e.tick for e in shifted.events] \
        == [min(base.ticks - 1, e.tick + 6) for e in base.events]
    # Same inputs, same spec bytes — and the base spec is untouched.
    again = apply_perturbations(base, (Perturbation("event_timing", 6.0),))
    assert shifted == again
    assert base == sample_future("cascading_failures", 5).replay_spec(24)
    with pytest.raises(ValueError, match="unknown perturbation"):
        apply_perturbations(base, (Perturbation("nope", 1.0),))


def test_fault_reorder_permutes_fault_ticks_only():
    base = sample_future("cascading_failures", 5).replay_spec(24)
    fault_kinds = {"kill_broker", "kill_logdir"}
    faults = [e for e in base.events if e.kind in fault_kinds]
    if len(faults) < 2:
        pytest.skip("sampled spec has <2 fault events")
    rot = apply_perturbations(base, (Perturbation("fault_reorder", 1.0),))
    rot_faults = [e for e in rot.events if e.kind in fault_kinds]
    # The tick multiset is preserved (rotation, not a shift) but WHICH
    # fault fires at which tick changes — the schedule permutes.
    assert sorted(e.tick for e in rot_faults) \
        == sorted(e.tick for e in faults)
    assert [(e.tick, e.kind, sorted(e.params.items()))
            for e in rot_faults] \
        != [(e.tick, e.kind, sorted(e.params.items())) for e in faults]
    others = [e for e in base.events if e.kind not in fault_kinds]
    rot_others = [e for e in rot.events if e.kind not in fault_kinds]
    assert others == rot_others


# ---------------------------------------------------------------------------
# Miner determinism: one sweep seed ⇒ byte-identical frontier JSON
# ---------------------------------------------------------------------------

@pytest.mark.parametrize(
    "sweep_seed",
    # Seed 0 (~17 s) is tier-2; seed 7 keeps the byte-pin tier-1.
    [pytest.param(0, marks=pytest.mark.slow), 7])
def test_frontier_json_byte_identical_per_sweep_seed(sweep_seed,
                                                     shared_optimizer):
    lib = {"stub_scenario": 0.25}
    r1 = mine(sweep_seed, optimizer=shared_optimizer, library=lib,
              **SWEEP_KW)
    r2 = mine(sweep_seed, optimizer=shared_optimizer, library=lib,
              **SWEEP_KW)
    assert frontier_json(r1) == frontier_json(r2)
    assert r1["frontier"], "toy sweep must keep at least one entry"
    assert r1["sweepSeed"] == sweep_seed
    assert r1["generationsRun"] == SWEEP_KW["generations"]
    assert not r1["partial"] and r1["partialReason"] is None
    assert r1["library"]["minMargin"] == 0.25
    # Frontier is sorted worst-first with deterministic ties.
    margins = [e["margin"] for e in r1["frontier"]]
    assert margins == sorted(margins)
    for e in r1["frontier"]:
        assert e["id"] == Candidate.from_dict(e).entry_id
        assert e["blindSpot"] is not None


def test_mine_eval_budget_exhaustion_is_partial_not_silent(
        shared_optimizer):
    r = mine(0, optimizer=shared_optimizer,
             **{**SWEEP_KW, "eval_budget": 3})
    assert r["partial"] is True
    assert "eval budget" in r["partialReason"]
    assert r["evals"] + r["replays"] <= 4     # one truncated generation


def test_mine_wall_budget_exhaustion_is_partial_not_silent(
        shared_optimizer):
    ticks = iter(range(1000))

    def fake_clock() -> float:
        return float(next(ticks))

    r = mine(0, optimizer=shared_optimizer, clock=fake_clock,
             budget_s=0.5, **SWEEP_KW)
    assert r["partial"] is True
    assert "wall budget" in r["partialReason"]


# ---------------------------------------------------------------------------
# Committed-frontier replay pins (the regression contract)
# ---------------------------------------------------------------------------

@pytest.mark.slow  # two full-loop 24-tick replays
@pytest.mark.parametrize("idx", [0, 1])
def test_committed_frontier_entry_replays_byte_identical(idx):
    committed = load_frontier(str(COMMITTED_FRONTIER))
    assert committed is not None, "committed frontier file missing"
    entries = committed["frontier"]
    assert len(entries) > idx, "committed frontier too small"
    entry = entries[idx]
    result = replay_entry(entry)
    digest = f"{zlib.crc32(result.score.to_json().encode()):08x}"
    assert digest == entry["scoreDigest"]
    assert result.assignment_digest == entry["assignmentDigest"]
    margins = result.score.slo_margins()
    assert round(scenario_margin(margins), 6) == entry["margin"]
    assert sorted(result.score.slo_violations()) \
        == sorted(entry["sloViolations"])


def test_committed_frontier_beats_library_minimum():
    """The acceptance bar: the miner found at least one scenario with a
    lower SLO margin than every hand-written canonical scenario."""
    committed = load_frontier(str(COMMITTED_FRONTIER))
    assert committed is not None, "committed frontier file missing"
    lib_min = committed["library"]["minMargin"]
    assert committed["foundBelowLibrary"] >= 1
    assert min(e["margin"] for e in committed["frontier"]) < lib_min


# ---------------------------------------------------------------------------
# Correlated multi-cluster cascade (testing/fleet_twin.py)
# ---------------------------------------------------------------------------

@pytest.mark.slow  # twin full-loop ticks
def test_fleet_correlated_cascade_heals_clean():
    from cruise_control_tpu.testing.fleet_twin import run_fleet_cascade
    r = run_fleet_cascade(seed=0, ticks=32)
    assert r["scenario"] == "fleet_correlated_cascade"
    assert r["events_applied"] == 2       # both kills land (same tick)
    # faults_injected counts CHAOS-schedule injections (none here);
    # scripted kills prove themselves through the heal accounting.
    assert r["time_to_heal_p95_ticks"] is not None
    assert r["unhealed_faults"] == 0
    assert r["dead_letters"] == 0
    assert r["slo_violations"] == []
    assert r["megabatch_batches"] > 0
    assert r["megabatch_last_occupancy"] == 2


# ---------------------------------------------------------------------------
# Forecaster blind-spot report (redteam/blindspot.py)
# ---------------------------------------------------------------------------

def test_forecast_miss_step_is_blind_spot_ramp_is_not():
    step = [1.0] * 12 + [3.0] * 12
    r = forecast_miss(step, 12)
    assert r["miss"] is True              # step after the fit window
    ramp = [1.0 + 0.05 * t for t in range(24)]
    r2 = forecast_miss(ramp, 12)
    assert r2["miss"] is False            # the trend basis extrapolates
    assert r2["maxDeviation"] <= r2["band"]


def test_entry_blind_spot_tags_near_violating_step_only():
    step_spec = ScenarioSpec(
        name="rt_step", description="", ticks=24,
        events=(ScenarioEvent(12, "set_load", {"factor": 3.0}),))
    tagged = entry_blind_spot(step_spec, margin=0.05)
    assert tagged["nearViolation"] and tagged["miss"] and tagged["tagged"]
    # Same trajectory, comfortable margin: measured but untagged.
    assert entry_blind_spot(step_spec, margin=0.5)["tagged"] is False
    flat_spec = ScenarioSpec(name="rt_flat", description="", ticks=24)
    flat = entry_blind_spot(flat_spec, margin=0.05)
    assert flat["nearViolation"] is True
    assert flat["miss"] is False and flat["tagged"] is False


def test_global_factor_series_applies_steps_and_phase():
    spec = ScenarioSpec(
        name="rt_series", description="", ticks=8,
        drift=DriftSpec(amplitude=0.5, period_ticks=8, phase_ticks=2.0),
        events=(ScenarioEvent(4, "set_load", {"factor": 2.0}),))
    series = global_factor_series(spec)
    assert len(series) == 8
    import math
    for t in (0, 3, 4, 7):
        base = 2.0 if t >= 4 else 1.0
        want = base * (1.0 + 0.5 * math.sin(2.0 * math.pi * (t + 2.0) / 8))
        assert series[t] == round(max(want, 0.01), 6)


# ---------------------------------------------------------------------------
# API surface: GET /redteam + what_if=mined:<id>
# ---------------------------------------------------------------------------

def _make_api(extra_config: dict):
    from cruise_control_tpu.api.server import CruiseControlApi
    from cruise_control_tpu.common.resources import Resource
    from cruise_control_tpu.config.cruise_control_config import (
        CruiseControlConfig,
    )
    from cruise_control_tpu.executor.admin import (
        InMemoryAdminBackend, PartitionState,
    )
    from cruise_control_tpu.executor.executor import Executor
    from cruise_control_tpu.facade import CruiseControl
    from cruise_control_tpu.monitor import (
        LoadMonitor, StaticCapacityResolver,
    )
    from cruise_control_tpu.monitor.sampling import SyntheticSampler
    parts = {}
    for t in range(2):
        for p in range(4):
            reps = (0, 1 + (t + p) % 3)
            parts[(f"t{t}", p)] = PartitionState(f"t{t}", p, reps,
                                                 reps[0], isr=reps)
    backend = InMemoryAdminBackend(parts.values())
    cfg = CruiseControlConfig({
        "partition.metrics.window.ms": 1000,
        "num.partition.metrics.windows": 3,
        "min.valid.partition.ratio": 0.0,
        "failed.brokers.file.path": "",
        **extra_config})
    caps = StaticCapacityResolver({}, {Resource.CPU: 100.0,
                                       Resource.DISK: 1e7,
                                       Resource.NW_IN: 1e6,
                                       Resource.NW_OUT: 1e6})
    monitor = LoadMonitor(cfg, backend, samplers=[SyntheticSampler()],
                          capacity_resolver=caps)
    cc = CruiseControl(cfg, backend, load_monitor=monitor,
                       executor=Executor(backend, synchronous=True))
    for k in range(1, 4):
        monitor.task_runner.run_sampling_once(end_ms=k * 1000)
    api = CruiseControlApi(cc)
    api._async_wait_s = 300
    return api, cc


@pytest.fixture(scope="module")
def mined_frontier(tmp_path_factory, shared_optimizer):
    """A real toy-scale mined frontier saved to a tmp path — the API
    fixtures point redteam.frontier.path here."""
    path = tmp_path_factory.mktemp("redteam") / "frontier.json"
    result = mine(0, optimizer=shared_optimizer, **SWEEP_KW)
    save_frontier(result, str(path))
    return str(path), result


@pytest.fixture(scope="module")
def redteam_api(mined_frontier):
    path, _result = mined_frontier
    api, cc = _make_api({"redteam.frontier.path": path})
    yield api, cc
    api.shutdown()


def test_redteam_endpoint_serves_frontier(redteam_api, mined_frontier):
    api, _cc = redteam_api
    _path, result = mined_frontier
    status, body, _ = api.handle("GET", "/kafkacruisecontrol/redteam", "")
    assert status == 200, body
    assert body["frontierFound"] is True
    assert body["sweepSeed"] == 0
    assert body["numEntries"] == len(result["frontier"])
    assert [e["id"] for e in body["frontier"]] \
        == [e["id"] for e in result["frontier"]]
    assert body["frontier"][0]["blindSpot"] is not None
    # entries= bounds, blind_spots=false strips the per-entry detail.
    status, body, _ = api.handle("GET", "/kafkacruisecontrol/redteam",
                                 "entries=1&blind_spots=false")
    assert status == 200
    assert body["numEntries"] == 1
    assert "blindSpot" not in body["frontier"][0]


def test_redteam_endpoint_missing_frontier_hints_at_miner():
    api, _cc = _make_api({"redteam.frontier.path": "/tmp/rt_nope.json"})
    try:
        status, body, _ = api.handle("GET",
                                     "/kafkacruisecontrol/redteam", "")
        assert status == 200
        assert body["frontierFound"] is False
        assert "bench.py --redteam" in body["hint"]
        status, body, _ = api.handle(
            "GET", "/kafkacruisecontrol/proposals", "what_if=mined:m0")
        assert status == 400
        assert "mined frontier is empty" in body["errorMessage"]
        assert "bench.py --redteam" in body["errorMessage"]
    finally:
        api.shutdown()


def test_what_if_mined_replays_frontier_entry(redteam_api, mined_frontier):
    api, _cc = redteam_api
    _path, result = mined_frontier
    entry = result["frontier"][0]
    status, body, _ = api.handle(
        "GET", "/kafkacruisecontrol/proposals",
        f"what_if=mined:{entry['id']}")
    assert status == 200, body
    assert body["dryrun"] is True and body["executed"] is False
    assert body["seed"] == entry["replaySeed"]
    assert body["ticks"] == entry["ticks"]
    assert body["finalAssignmentDigest"] == entry["assignmentDigest"]
    digest = f"{zlib.crc32(json.dumps(body['score'], sort_keys=True).encode()):08x}"
    assert digest == entry["scoreDigest"]


def test_what_if_mined_unknown_id_lists_known_ids(redteam_api,
                                                  mined_frontier):
    api, _cc = redteam_api
    _path, result = mined_frontier
    status, body, _ = api.handle("GET", "/kafkacruisecontrol/proposals",
                                 "what_if=mined:zzz")
    assert status == 400
    msg = body["errorMessage"]
    assert "unknown mined frontier id 'zzz'" in msg
    for e in result["frontier"]:
        assert e["id"] in msg


# ---------------------------------------------------------------------------
# Off means off
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def disabled_api(mined_frontier):
    path, _result = mined_frontier
    api, cc = _make_api({"redteam.enabled": False,
                         "redteam.frontier.path": path})
    yield api, cc
    api.shutdown()


def test_redteam_disabled_surfaces_400(disabled_api, mined_frontier):
    api, _cc = disabled_api
    _path, result = mined_frontier
    status, body, _ = api.handle("GET", "/kafkacruisecontrol/redteam", "")
    assert status == 400
    assert "redteam.enabled=false" in body["errorMessage"]
    status, body, _ = api.handle(
        "GET", "/kafkacruisecontrol/proposals",
        f"what_if=mined:{result['frontier'][0]['id']}")
    assert status == 400
    assert "redteam.enabled=false" in body["errorMessage"]


def test_redteam_disabled_leaves_proposal_bytes_unchanged(redteam_api,
                                                          disabled_api):
    """Off means off: the same what_if replay request returns BYTE-
    identical proposal bodies whether redteam is enabled or disabled —
    the subsystem adds a surface, it never perturbs the existing one."""
    q = "what_if=random:load_ramp:3&what_if_ticks=6"
    _s1, b1, _ = redteam_api[0].handle(
        "GET", "/kafkacruisecontrol/proposals", q)
    _s2, b2, _ = disabled_api[0].handle(
        "GET", "/kafkacruisecontrol/proposals", q)
    assert json.dumps(b1, sort_keys=True) == json.dumps(b2, sort_keys=True)


def test_redteam_disabled_leaves_loadgen_schedule_digest_pinned():
    """The serving loadgen schedule is untouched by the red-team
    subsystem: the bench_baseline.json digest pin holds with
    redteam.enabled=false (same pin test_serving asserts by default)."""
    from cruise_control_tpu.serving import loadgen
    profile = loadgen.mixed_profile()
    s = loadgen.generate_schedule(profile, seed=0, rate_rps=50.0,
                                  duration_s=2.0)
    assert loadgen.schedule_digest(s) == "3318f2f9"


# ---------------------------------------------------------------------------
# Frontier persistence round-trip
# ---------------------------------------------------------------------------

def test_frontier_save_load_round_trip(tmp_path, mined_frontier):
    _path, result = mined_frontier
    p = tmp_path / "nested" / "frontier.json"
    save_frontier(result, str(p))
    loaded = load_frontier(str(p))
    assert frontier_json(loaded) == frontier_json(result)
    entry = loaded["frontier"][0]
    spec = entry_spec(entry)
    assert spec.ticks == entry["ticks"]
    assert load_frontier(str(tmp_path / "missing.json")) is None
