"""Pipeline tracing + XLA/device telemetry (round 8).

Unit coverage of the span tracer (nesting, ring bound, disabled no-op,
JSONL dump), the OperationProgress fixes (idempotent done, live
completion estimate), and the end-to-end acceptance bar: one rebalance
dry-run against the in-memory fixture yields ONE trace tree —
aggregate → model (cache hit/miss + transfer bytes) → per-goal solve →
proposal diff — retrievable from GET /kafkacruisecontrol/trace, with
well-formed per-stage ``_bucket`` histograms plus ``xla_compile_seconds``
and ``device_memory_bytes`` series on /metrics."""

import json
import threading
import time

import pytest

from cruise_control_tpu.api.server import CruiseControlApi
from cruise_control_tpu.common.resources import Resource
from cruise_control_tpu.config.cruise_control_config import CruiseControlConfig
from cruise_control_tpu.executor.admin import InMemoryAdminBackend, PartitionState
from cruise_control_tpu.executor.executor import Executor
from cruise_control_tpu.facade import CruiseControl
from cruise_control_tpu.monitor import LoadMonitor, StaticCapacityResolver
from cruise_control_tpu.monitor.sampling import SyntheticSampler
from cruise_control_tpu.utils.progress import OperationProgress
from cruise_control_tpu.utils.tracing import TRACER, Tracer, span_names


# ---- tracer unit behavior ------------------------------------------------

def test_span_nesting_and_attributes():
    tracer = Tracer(max_traces=8)
    with tracer.span("root", operation="op") as r:
        with tracer.span("child") as c:
            c.set(k=1)
            with tracer.span("grandchild"):
                tracer.annotate(deep=True)
        r.set(done=True)
    traces = tracer.traces()
    assert len(traces) == 1
    t = traces[0]
    assert t["operation"] == "op"
    assert t["spanCount"] == 3
    assert span_names(t) == ["root", "child", "grandchild"]
    child = t["root"]["children"][0]
    assert {"key": "k", "value": {"intValue": "1"}} in child["attributes"]
    grand = child["children"][0]
    assert {"key": "deep", "value": {"boolValue": True}} in grand["attributes"]
    # OTLP-compatible ids: 32-hex trace id shared, distinct 16-hex span ids
    assert len(t["traceId"]) == 32
    ids = {t["root"]["spanId"], child["spanId"], grand["spanId"]}
    assert len(ids) == 3 and all(len(i) == 16 for i in ids)
    assert child["parentSpanId"] == t["root"]["spanId"]


def test_ring_bound_and_filters():
    tracer = Tracer(max_traces=2)
    for i in range(4):
        with tracer.span(f"op{i}", operation=f"op{i}"):
            pass
    traces = tracer.traces()
    assert [t["operation"] for t in traces] == ["op3", "op2"]
    assert tracer.traces(operation="op3")[0]["operation"] == "op3"
    assert tracer.traces(operation="op0") == []
    assert tracer.traces(limit=1)[0]["operation"] == "op3"
    assert tracer.traces(limit=0) == []


def test_disabled_records_nothing_and_is_reentrant():
    tracer = Tracer()
    tracer.configure(enabled=False)
    with tracer.span("a") as s:
        s.set(x=1)  # the null span accepts set()
        with tracer.span("b"):
            tracer.annotate(y=2)
        tracer.record_span("c", 0.1)
    assert tracer.traces() == []
    assert tracer.spans_closed == 0
    # the disabled path hands back one shared object — no per-call alloc
    assert tracer.span("a") is tracer.span("b")


def test_record_span_attaches_pre_timed_child():
    tracer = Tracer()
    with tracer.span("root"):
        tracer.record_span("goal.solve", 0.25, goal="RackAwareGoal",
                           apportioned=True)
    t = tracer.traces()[0]
    goal = t["root"]["children"][0]
    assert goal["name"] == "goal.solve"
    assert 200 <= goal["durationMs"] <= 300
    assert {"key": "goal", "value": {"stringValue": "RackAwareGoal"}} \
        in goal["attributes"]


def test_exception_marks_span_and_propagates():
    tracer = Tracer()
    with pytest.raises(ValueError):
        with tracer.span("boom", operation="x"):
            raise ValueError("nope")
    t = tracer.traces()[0]
    assert {"key": "error", "value": {"stringValue": "ValueError"}} \
        in t["root"]["attributes"]


def test_jsonl_dump(tmp_path):
    path = tmp_path / "trace.jsonl"
    tracer = Tracer()
    tracer.configure(jsonl_path=str(path))
    with tracer.span("a", operation="bench"):
        with tracer.span("b"):
            pass
    with tracer.span("c", operation="bench"):
        pass
    lines = [json.loads(ln) for ln in path.read_text().splitlines()]
    assert len(lines) == 2
    assert lines[0]["spanCount"] == 2
    assert span_names(lines[0]) == ["a", "b"]


def test_operation_filter_matches_nested_operations():
    # Fleet mode: the scheduler's fleet.job wrapper is the trace ROOT and
    # the routed runnable ("rebalance") nests under it — the operation
    # filter must still find the trace by the nested runnable name.
    tracer = Tracer()
    with tracer.span("fleet.job", operation="fleet.on_demand",
                     cluster="alpha"):
        with tracer.span("rebalance", operation="rebalance"):
            pass
    assert tracer.traces(operation="rebalance"), \
        "fleet-wrapped operations must stay filterable by runnable name"
    assert tracer.traces(operation="fleet.on_demand")
    t = tracer.traces()[0]
    assert t["operation"] == "fleet.on_demand"  # the root stays primary
    assert set(t["operations"]) == {"fleet.on_demand", "rebalance"}


def test_cross_thread_spans_become_roots():
    tracer = Tracer()
    done = threading.Event()

    def worker():
        with tracer.span("worker.job", operation="background"):
            pass
        done.set()

    with tracer.span("main.op", operation="main"):
        t = threading.Thread(target=worker)
        t.start()
        t.join()
    assert done.wait(1)
    ops = {t["operation"] for t in tracer.traces()}
    assert ops == {"main", "background"}


# ---- OperationProgress satellites ---------------------------------------

def test_progress_done_idempotent():
    p = OperationProgress("op")
    p.start_step("A")
    time.sleep(0.01)
    p.done()
    first = p.to_list()[0]["durationS"]
    time.sleep(0.02)
    p.done()  # re-entered done() must not overwrite the duration
    assert p.to_list()[0]["durationS"] == first
    assert p.to_list()[0]["completionPercentage"] == 100.0


def test_progress_live_completion_estimate():
    p = OperationProgress("op")
    p.start_step("Model", estimate_s=0.05)
    time.sleep(0.02)
    live = p.to_list()[0]["completionPercentage"]
    assert 10.0 <= live < 100.0, \
        f"in-flight step with an estimate must report progress, got {live}"
    time.sleep(0.06)
    assert p.to_list()[0]["completionPercentage"] == 99.0  # clamped
    p.done()
    assert p.to_list()[0]["completionPercentage"] == 100.0


def test_progress_without_estimate_stays_zero():
    p = OperationProgress("op")
    p.start_step("NoEstimate")
    assert p.to_list()[0]["completionPercentage"] == 0.0


# ---- end-to-end: rebalance trace + telemetry exposition ------------------

def _partitions(brokers=(0, 1, 2, 3), topics=2, parts=4):
    out = {}
    for t in range(topics):
        for p in range(parts):
            reps = (brokers[0], brokers[1 + (t + p) % (len(brokers) - 1)])
            out[(f"t{t}", p)] = PartitionState(f"t{t}", p, reps, reps[0],
                                               isr=reps)
    return out


@pytest.fixture(scope="module")
def traced_api():
    partitions = _partitions()
    backend = InMemoryAdminBackend(partitions.values())
    cfg = CruiseControlConfig({
        "partition.metrics.window.ms": 1000,
        "num.partition.metrics.windows": 3,
        "min.valid.partition.ratio": 0.0,
        "max.solver.rounds": 30,
        "failed.brokers.file.path": ""})
    caps = StaticCapacityResolver({}, {Resource.CPU: 100.0, Resource.DISK: 1e7,
                                       Resource.NW_IN: 1e6,
                                       Resource.NW_OUT: 1e6})
    monitor = LoadMonitor(cfg, backend, samplers=[SyntheticSampler()],
                          capacity_resolver=caps)
    cc = CruiseControl(cfg, backend, load_monitor=monitor,
                       executor=Executor(backend, synchronous=True))
    for k in range(1, 4):
        monitor.task_runner.run_sampling_once(end_ms=k * 1000)
    api = CruiseControlApi(cc)
    api._async_wait_s = 180
    yield api
    api.shutdown()
    TRACER.configure(enabled=True, jsonl_path=None)


def test_rebalance_dryrun_yields_full_trace_tree(traced_api):
    assert TRACER.enabled  # facade wired tracing.enabled from config
    status, body, _ = traced_api.handle(
        "POST", "/kafkacruisecontrol/rebalance", "dryrun=true")
    assert status == 200, body
    status, body, _ = traced_api.handle(
        "GET", "/kafkacruisecontrol/trace", "operation=rebalance&entries=1")
    assert status == 200, body
    assert body["tracingEnabled"] is True
    assert body["numTraces"] == 1
    trace = body["traces"][0]
    names = span_names(trace)
    assert names[0] == "rebalance"
    for expected in ("monitor.cluster_model", "monitor.aggregate",
                     "model.assemble", "analyzer.optimize", "goal.solve",
                     "analyzer.proposal_diff"):
        assert expected in names, f"missing {expected} in {names}"
    assert names.count("goal.solve") >= 2, "per-goal spans expected"

    def find(node, name):
        if node["name"] == name:
            return node
        for c in node["children"]:
            hit = find(c, name)
            if hit is not None:
                return hit
        return None

    assemble = find(trace["root"], "model.assemble")
    attrs = {a["key"]: a["value"] for a in assemble["attributes"]}
    assert "topology_hit" in attrs, "cache hit/miss must be attributed"
    assert "transfer_bytes" in attrs
    assert int(attrs["transfer_bytes"]["intValue"]) > 0
    goal = find(trace["root"], "goal.solve")
    gattrs = {a["key"]: a["value"] for a in goal["attributes"]}
    assert "goal" in gattrs and "candidates" in gattrs


def test_sampling_fetch_traces_recorded(traced_api):
    assert TRACER.traces(operation="sampling"), \
        "each sampling cycle should record its own fetch trace"


def test_metrics_expose_histograms_and_device_telemetry(traced_api):
    # Run at least one traced operation first (module fixture already did).
    text = traced_api.metrics_text()
    # per-stage span histograms, well-formed
    for stage in ("monitor.aggregate", "model.assemble", "goal.solve",
                  "analyzer.optimize"):
        assert (f'kafka_cruisecontrol_trace_span_seconds_bucket'
                f'{{span="{stage}",le="+Inf"}}') in text, stage
    assert "# TYPE kafka_cruisecontrol_trace_span_seconds histogram" in text
    # XLA compile telemetry (per padded-shape labels)
    assert "kafka_cruisecontrol_xla_compile_seconds_bucket" in text
    assert 'shape="' in text
    # device memory gauges exist on every backend (CPU falls back to the
    # live-array footprint)
    assert "kafka_cruisecontrol_device_memory_bytes{" in text
    # transfer accounting from the model pipeline
    assert "kafka_cruisecontrol_device_transfer_bytes_total" in text
    # No duplicate sample lines anywhere: Prometheus rejects the whole
    # scrape if one series (name + label set) appears twice.
    samples = [ln.split(" ")[0] for ln in text.splitlines()
               if ln and not ln.startswith("#")]
    dupes = {s for s in samples if samples.count(s) > 1}
    assert not dupes, f"duplicate series in /metrics: {sorted(dupes)[:5]}"


def test_trace_endpoint_cluster_filter_no_fleet(traced_api):
    # ?cluster= on /trace FILTERS by recorded label (no fleet required;
    # nothing in this fixture ran under a cluster label).
    status, body, _ = traced_api.handle(
        "GET", "/kafkacruisecontrol/trace", "cluster=nosuch")
    assert status == 200
    assert body["numTraces"] == 0


def test_tracing_disabled_no_new_traces(traced_api):
    TRACER.configure(enabled=False)
    try:
        before = TRACER.spans_closed
        status, _body, _ = traced_api.handle(
            "POST", "/kafkacruisecontrol/rebalance", "dryrun=true")
        assert status == 200
        assert TRACER.spans_closed == before
        status, body, _ = traced_api.handle(
            "GET", "/kafkacruisecontrol/trace", "")
        assert status == 200 and body["tracingEnabled"] is False
    finally:
        TRACER.configure(enabled=True)


def test_jsonl_rotation_caps_file_size(tmp_path):
    """tracing.jsonl.max.bytes: an append that would push the dump past
    the cap rotates the file to <path>.1 first (one rotated generation
    kept — total footprint bounded at ~2x the cap); an unlimited cap (0)
    never rotates."""
    path = tmp_path / "trace.jsonl"
    tracer = Tracer()
    tracer.configure(jsonl_path=str(path))
    with tracer.span("sizer", operation="bench"):
        pass
    line_size = len(path.read_text())
    # Cap at ~2.5 lines: the 3rd close must rotate.
    tracer.configure(jsonl_max_bytes=int(2.5 * line_size))
    path.write_text("")  # restart the dump empty
    for _ in range(3):
        with tracer.span("sizer", operation="bench"):
            pass
    rotated = tmp_path / "trace.jsonl.1"
    assert rotated.exists(), "rotation did not happen"
    assert tracer.jsonl_rotations == 1
    assert len((rotated).read_text().splitlines()) == 2
    assert len(path.read_text().splitlines()) == 1
    # Every line in both generations is still valid JSON.
    for f in (path, rotated):
        for ln in f.read_text().splitlines():
            json.loads(ln)
    # A second overflow replaces the rotated generation (bounded at one).
    for _ in range(2):
        with tracer.span("sizer", operation="bench"):
            pass
    assert tracer.jsonl_rotations == 2
    assert len(rotated.read_text().splitlines()) == 2


def test_jsonl_no_rotation_when_unlimited(tmp_path):
    path = tmp_path / "trace.jsonl"
    tracer = Tracer()
    tracer.configure(jsonl_path=str(path), jsonl_max_bytes=0)
    for _ in range(5):
        with tracer.span("a", operation="bench"):
            pass
    assert not (tmp_path / "trace.jsonl.1").exists()
    assert len(path.read_text().splitlines()) == 5


# ---- xla_telemetry unit coverage (round 12 satellite) --------------------

def test_device_memory_bytes_cpu_live_array_fallback():
    """CPU backends have no allocator stats: refresh_device_gauges must
    fall back to the summed live jax.Array footprint so the
    device_memory_bytes series exists everywhere."""
    import jax.numpy as jnp

    from cruise_control_tpu.utils import xla_telemetry
    from cruise_control_tpu.utils.sensors import SENSORS

    keep = jnp.ones((256, 4), jnp.float32)  # ≥ 4 KB live on the device
    xla_telemetry.refresh_device_gauges()
    gauges = {k: v for k, v in SENSORS._gauges.items()
              if k[0] == "device_memory_bytes"}
    assert gauges, "no device_memory_bytes series on CPU"
    cpu_in_use = [(k, v) for k, v in gauges.items()
                  if ("kind", "bytes_in_use") in k[1]
                  and any(lk == "device" and lv.startswith("cpu")
                          for lk, lv in k[1])]
    assert cpu_in_use, f"no cpu bytes_in_use gauge in {list(gauges)}"
    assert max(v for _k, v in cpu_in_use) >= keep.nbytes


def test_record_dispatch_counter_and_histogram_labels():
    from cruise_control_tpu.utils import xla_telemetry
    from cruise_control_tpu.utils.sensors import SENSORS

    def counter(name, kind):
        return SENSORS._counters.get((name, (("kind", kind),)), 0.0)

    base = counter("solver_dispatches", "move")
    base_don = counter("solver_dispatch_donations", "move")
    base_spec = counter("solver_dispatch_speculative", "move")
    snap0 = SENSORS.histogram_snapshot("solver_dispatch_rounds",
                                       labels={"kind": "move"})
    count0 = snap0["count"] if snap0 else 0
    xla_telemetry.record_dispatch("move", rounds=12, donated=True)
    xla_telemetry.record_dispatch("move", rounds=3, speculative=True)
    assert counter("solver_dispatches", "move") == base + 2
    assert counter("solver_dispatch_donations", "move") == base_don + 1
    assert counter("solver_dispatch_speculative", "move") == base_spec + 1
    snap = SENSORS.histogram_snapshot("solver_dispatch_rounds",
                                      labels={"kind": "move"})
    assert snap["count"] == count0 + 2
    assert snap["buckets"] == xla_telemetry.DISPATCH_ROUND_BUCKETS
    # swap dispatches land in their OWN labeled series
    swap_base = counter("solver_dispatches", "swap")
    xla_telemetry.record_dispatch("swap", rounds=1)
    assert counter("solver_dispatches", "swap") == swap_base + 1


def test_record_dispatch_annotates_ambient_span():
    from cruise_control_tpu.utils import xla_telemetry
    tracer_was = TRACER.enabled
    TRACER.configure(enabled=True)
    try:
        with TRACER.span("goal.solve") as sp:
            xla_telemetry.record_dispatch("move", rounds=4)
            xla_telemetry.record_dispatch("move", rounds=4)
            assert sp.attributes["dispatches"] == 2
    finally:
        TRACER.configure(enabled=tracer_was)


def test_jsonl_rotation_cascade_keeps_max_files_generations(tmp_path):
    """tracing.jsonl.max.files: each overflow cascades .{N-1}->.N down to
    path->.1, keeping exactly max_files rotated generations (total
    footprint ~(max_files+1)x the cap); jsonl_rotations counts every
    generation MOVED, so a deep cascade is more than one per overflow."""
    path = tmp_path / "trace.jsonl"
    tracer = Tracer()
    tracer.configure(jsonl_path=str(path))
    with tracer.span("sizer", operation="bench"):
        pass
    line_size = len(path.read_text())
    tracer.configure(jsonl_max_bytes=int(1.5 * line_size),
                     jsonl_max_files=2)
    path.write_text("")  # restart the dump empty
    # Overflow #1: path -> .1 (one move).
    for _ in range(2):
        with tracer.span("sizer", operation="bench"):
            pass
    assert (tmp_path / "trace.jsonl.1").exists()
    assert not (tmp_path / "trace.jsonl.2").exists()
    assert tracer.jsonl_rotations == 1
    # Overflow #2 cascades: .1 -> .2, then path -> .1 (two moves).
    with tracer.span("sizer", operation="bench"):
        pass
    assert (tmp_path / "trace.jsonl.2").exists()
    assert tracer.jsonl_rotations == 3
    # Overflow #3: .2 is replaced (the ring is bounded at max_files);
    # every surviving generation holds exactly one valid-JSON line.
    with tracer.span("sizer", operation="bench"):
        pass
    assert tracer.jsonl_rotations == 5
    assert not (tmp_path / "trace.jsonl.3").exists()
    for f in (path, tmp_path / "trace.jsonl.1", tmp_path / "trace.jsonl.2"):
        lines = f.read_text().splitlines()
        assert len(lines) == 1
        json.loads(lines[0])
