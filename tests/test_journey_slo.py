"""Request journeys + SLO burn-rate engine (observability round).

Unit coverage: the journey record/ring/ambient-scope machinery and the
multi-window SLO registry, both on injected clocks; the SLO_BURN
detector lifecycle (confirm -> fix -> budget-recovered clear) through a
real heal ledger.

Integration coverage: off-means-off byte-identity of GET /proposals at
two partition shapes with observation on vs off, the GET /journeys and
GET /slo endpoints through the real api, loadgen segment attribution,
and twin ScenarioScore floor verdicts staying byte-identical to the
shared utils.slo renderer at two seeds."""

import json

import pytest

from cruise_control_tpu.api.server import CruiseControlApi
from cruise_control_tpu.common.resources import Resource
from cruise_control_tpu.config.cruise_control_config import CruiseControlConfig
from cruise_control_tpu.detector.slo_burn import SloBurnDetector
from cruise_control_tpu.executor.admin import InMemoryAdminBackend, PartitionState
from cruise_control_tpu.executor.executor import Executor
from cruise_control_tpu.facade import CruiseControl
from cruise_control_tpu.monitor import LoadMonitor, StaticCapacityResolver
from cruise_control_tpu.monitor.sampling import SyntheticSampler
from cruise_control_tpu.serving import loadgen
from cruise_control_tpu.serving.journey import (
    NO_JOURNEY, JourneyLog, current_journey, journey_scope,
    segment_attribution,
)
from cruise_control_tpu.utils.heal_ledger import HealLedger
from cruise_control_tpu.utils.slo import (
    DEFAULT_WINDOWS_S, Objective, SloRegistry, scenario_floor_violations,
)


class _Clock:
    """Injected monotonic/wall seam for deterministic journeys/windows."""

    def __init__(self, t: float = 1_000_000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> float:
        self.t += dt
        return self.t


# ---- journeys ------------------------------------------------------------

def test_disabled_log_returns_shared_null_and_records_nothing():
    log = JourneyLog(enabled=False)
    jny = log.open("PROPOSALS", cluster="alpha")
    assert jny is NO_JOURNEY
    assert not jny.recording
    # Every stamp site calls through unconditionally; all must no-op.
    jny.add("solve", 1.0)
    jny.note(outcome="ok")
    with jny.seg("render") as seg:
        seg.set(numProposals=3)
    log.close(jny)
    assert log.entries() == []
    assert log.stats() == {"journeysEnabled": False, "journeysOpened": 0,
                           "journeysClosed": 0, "ringSize": 0}


def test_segments_and_attribution_on_injected_clock():
    clk = _Clock()
    log = JourneyLog(enabled=True, monotonic=clk, clock=clk)
    jny = log.open("PROPOSALS", cluster="alpha")
    with jny.seg("solve", chain="default") as seg:
        clk.advance(2.0)
        seg.set(passSeqs=[7])
    jny.add("queue_wait", 0.5, klass="SOLVER")  # timed on another thread
    clk.advance(1.0)                            # deliberately unattributed
    jny.note(outcome="ok", coalesce="leader")
    log.close(jny)

    (entry,) = log.entries()
    assert entry["endpoint"] == "PROPOSALS"
    assert entry["cluster"] == "alpha"
    assert entry["status"] == "ok"
    assert entry["totalS"] == pytest.approx(3.0)
    assert entry["attributes"] == {"outcome": "ok", "coalesce": "leader"}
    segs = {s["segment"]: s for s in entry["segments"]}
    assert segs["solve"]["seconds"] == pytest.approx(2.0)
    assert segs["solve"]["passSeqs"] == [7]
    assert segs["queue_wait"]["klass"] == "SOLVER"
    # The remainder is REPORTED, never hidden.
    assert entry["unattributedS"] == pytest.approx(0.5)

    table = segment_attribution(log.entries())
    assert table["journeys"] == 1
    assert table["wall_s"] == pytest.approx(3.0)
    assert table["attributed_s"] == pytest.approx(2.5)
    assert table["unattributed_s"] == pytest.approx(0.5)
    assert table["attributed_fraction"] == pytest.approx(2.5 / 3.0, abs=1e-4)
    assert table["segments"]["solve"]["count"] == 1


def test_ring_is_bounded_and_newest_first():
    clk = _Clock()
    log = JourneyLog(enabled=True, max_entries=3, monotonic=clk, clock=clk)
    for i in range(7):
        jny = log.open(f"EP{i}")
        clk.advance(0.1)
        log.close(jny)
    entries = log.entries()
    assert [e["endpoint"] for e in entries] == ["EP6", "EP5", "EP4"]
    assert log.stats()["ringSize"] == 3
    assert log.stats()["journeysClosed"] == 7
    # endpoint filter + limit both apply on the export path.
    assert log.entries(endpoint="EP5")[0]["endpoint"] == "EP5"
    assert len(log.entries(limit=2)) == 2


def test_stamps_after_close_are_dropped():
    clk = _Clock()
    log = JourneyLog(enabled=True, monotonic=clk, clock=clk)
    jny = log.open("STATE")
    clk.advance(1.0)
    log.close(jny)
    jny.add("late_solve", 9.0)      # a 202's solve finishing after return
    jny.note(outcome="late")
    log.close(jny, status="error")  # double close ignored
    (entry,) = log.entries()
    assert entry["segments"] == []
    assert entry["attributes"] == {}
    assert entry["status"] == "ok"
    assert log.stats()["journeysClosed"] == 1


def test_segment_scope_records_error_type():
    clk = _Clock()
    log = JourneyLog(enabled=True, monotonic=clk, clock=clk)
    jny = log.open("REBALANCE")
    with pytest.raises(ValueError):
        with jny.seg("solve"):
            clk.advance(0.25)
            raise ValueError("boom")
    log.close(jny, status="error")
    (entry,) = log.entries()
    (seg,) = entry["segments"]
    assert seg["segment"] == "solve"
    assert seg["error"] == "ValueError"
    assert seg["seconds"] == pytest.approx(0.25)


def test_ambient_scope_is_null_outside_and_rewraps():
    assert current_journey() is NO_JOURNEY
    log = JourneyLog(enabled=True)
    jny = log.open("LOAD")
    with journey_scope(jny):
        assert current_journey() is jny
        # The engine-worker rewrap discipline: a nested scope with the
        # null journey must make deep stamps no-op, not leak the outer.
        with journey_scope(NO_JOURNEY):
            assert current_journey() is NO_JOURNEY
        assert current_journey() is jny
    assert current_journey() is NO_JOURNEY


# ---- SLO registry --------------------------------------------------------

def _registry(objectives, clk, **kw):
    kw.setdefault("windows_s", DEFAULT_WINDOWS_S)
    return SloRegistry(objectives, enabled=True, clock=clk, **kw)


def test_empty_windows_burn_zero_never_nan():
    clk = _Clock()
    reg = _registry([Objective("error", "error", budget=0.01)], clk)
    rates = reg.burn_rates("error")
    assert set(rates) == set(DEFAULT_WINDOWS_S)
    assert all(r == 0.0 for r in rates.values())
    assert reg.budget_remaining("error") == 1.0
    assert reg.burning("error") is False
    # The full evaluation must serialize with allow_nan=False.
    json.dumps(reg.state(), allow_nan=False)


def test_record_request_classifies_into_every_kind():
    clk = _Clock()
    reg = _registry(
        [Objective("latency", "latency", budget=0.05, threshold_s=2.0),
         Objective("error", "error", budget=0.01),
         Objective("shed", "shed", budget=0.05)], clk)
    reg.record_request(0.1, 200)    # fast success: all good
    reg.record_request(5.0, 200)    # slow success: latency bad
    reg.record_request(0.1, 500)    # error bad; latency NOT recorded
    reg.record_request(0.1, 429)    # shed bad; neither latency nor error
    w = max(DEFAULT_WINDOWS_S)
    assert reg.burn_rates("latency")[w] == pytest.approx((1 / 2) / 0.05)
    assert reg.burn_rates("error")[w] == pytest.approx((1 / 4) / 0.01)
    assert reg.burn_rates("shed")[w] == pytest.approx((1 / 4) / 0.05)
    # 25x error burn exhausts the 1% budget: remaining clamps to 0.
    assert reg.budget_remaining("error") == 0.0


def test_multi_window_rule_needs_both_windows_of_a_pair():
    clk = _Clock()
    # Windows: fast pair (300s, 3600s), slow pair (1800s, 21600s).
    reg = _registry([Objective("shed", "shed", budget=0.01)], clk)
    for _ in range(20):
        reg.record("shed", True)
    # All events recent: every window burns 100x -> both pairs fire.
    assert reg.burning("shed") is True
    # Age the events past the 300s fast window: the fast pair loses its
    # short window but the slow pair (1800s + 21600s) still agrees.
    clk.advance(400.0)
    rates = reg.burn_rates("shed")
    assert rates[300.0] == 0.0 and rates[1800.0] > 6.0
    assert reg.burning("shed") is True
    # Age past 1800s: only the two LONG windows still hold events — one
    # window per pair is not a verdict, so the burn is over.
    clk.advance(1700.0)
    rates = reg.burn_rates("shed")
    assert rates[3600.0] > 0.0 and rates[21600.0] > 0.0
    assert reg.burning("shed") is False


def test_disabled_registry_records_nothing():
    clk = _Clock()
    reg = SloRegistry([Objective("shed", "shed", budget=0.01)],
                      enabled=False, clock=clk)
    reg.record_request(9.0, 429)
    reg.record("shed", True)
    reg.observe_staleness(1e6)
    reg.observe_heal(1e6)
    assert reg.events_recorded == 0
    assert reg.burning("shed") is False


def test_from_config_reads_the_slo_surface():
    cfg = CruiseControlConfig({
        "slo.enabled": True,
        "slo.objectives": ["latency", "error", "shed", "staleness", "heal"],
        "slo.burn.windows": ["60", "600", "300", "3600"],
        "slo.objectives.shed.budget": 0.02,
    })
    reg = SloRegistry.from_config(cfg)
    assert reg.enabled
    assert reg.windows_s == (60.0, 600.0, 300.0, 3600.0)
    by_name = {o.name: o for o in reg.objectives()}
    assert sorted(by_name) == ["error", "heal", "latency", "shed",
                               "staleness"]
    assert by_name["shed"].budget == 0.02
    assert by_name["latency"].threshold_s == 2.0
    assert reg.fast_threshold == 14.4 and reg.slow_threshold == 6.0


def test_objective_validation():
    with pytest.raises(ValueError, match="unknown objective kind"):
        SloRegistry([Objective("x", "nope", budget=0.1)])
    with pytest.raises(ValueError, match="budget"):
        SloRegistry([Objective("error", "error", budget=0.0)])
    with pytest.raises(ValueError, match="windows_s"):
        SloRegistry(windows_s=(300.0, 3600.0))


# ---- burn detector lifecycle (injected clock, real heal ledger) ----------

def _burn_rig(clk, objectives):
    reg = SloRegistry(objectives, enabled=True, clock=clk)
    ledger = HealLedger(clock=clk)

    def report(anomaly):
        # detector/manager.py's report seam: the heal chain opens at
        # detection, keyed by the objective signature.
        ledger.open(anomaly.anomaly_type.name, anomaly.anomaly_id,
                    (anomaly.objective,))

    det = SloBurnDetector(reg, report, ledger=ledger)
    return reg, ledger, det


def test_slo_burn_lifecycle_confirm_then_budget_recovered_clear():
    clk = _Clock()
    reg, ledger, det = _burn_rig(
        clk, [Objective("shed", "shed", budget=0.01),
              Objective("heal", "heal", budget=0.1, threshold_s=600.0)])
    # Quiet tick: nothing raised, nothing open.
    assert det.run_once() is None
    assert det.state() == {"openBurns": [], "burnsRaised": 0,
                           "burnsCleared": 0}
    # 20 sheds -> 100x burn on every window: ONE anomaly, chain opens
    # with the live rates stamped on its first phase.
    for _ in range(20):
        reg.record("shed", True)
    anomaly = det.run_once()
    assert anomaly is not None and anomaly.objective == "shed"
    assert anomaly.fast_burn == pytest.approx(100.0)
    assert anomaly.budget_remaining == 0.0
    assert "shed" in anomaly.reasons()[0]
    # Standing burn: the next tick raises NOTHING new (signature alias).
    clk.advance(5.0)
    assert det.run_once() is None
    assert det.state()["openBurns"] == ["shed"]
    assert det.state()["burnsRaised"] == 1
    (chain,) = ledger.chains(anomaly_type="SLO_BURN")
    assert chain["outcome"] is None      # still open

    burning = next(p for p in chain["phases"] if p["phase"] == "burning")
    assert burning["objective"] == "shed"
    assert burning["fastBurn"] == pytest.approx(100.0)
    # Recovery: dilute the bad fraction under the slow threshold
    # (20/420 = 4.8% -> 4.8x < 6.0x) and tick again -> terminal clear.
    clk.advance(5.0)
    for _ in range(400):
        reg.record("shed", False)
    assert det.run_once() is None
    assert det.state() == {"openBurns": [], "burnsRaised": 1,
                           "burnsCleared": 1}
    (chain,) = ledger.chains(anomaly_type="SLO_BURN")
    assert chain["outcome"] == "cleared"
    assert chain["phases"][-1]["via"] == "budget_recovered"
    # Heal durations ride the injected clock exactly: opened at t,
    # cleared at t+10s.
    assert chain["healSeconds"] == pytest.approx(10.0)
    # The NEXT tick feeds that cleared chain into the time-to-heal
    # objective (10s < 600s threshold -> a good event).
    det.run_once()
    w = max(DEFAULT_WINDOWS_S)
    assert reg.burn_rates("heal")[w] == 0.0
    assert reg.state()["eventsHeld"]["heal"] == 1


def test_slo_burn_detector_re_raises_after_a_clear():
    clk = _Clock()
    reg, ledger, det = _burn_rig(clk,
                                 [Objective("shed", "shed", budget=0.01)])
    for _ in range(20):
        reg.record("shed", True)
    assert det.run_once() is not None
    # Everything ages out -> clear; then a FRESH burn is a NEW incident
    # (new chain: the old one is terminal, so no signature alias).
    clk.advance(30_000.0)
    assert det.run_once() is None
    assert det.state()["burnsCleared"] == 1
    for _ in range(20):
        reg.record("shed", True)
    assert det.run_once() is not None
    assert det.state()["burnsRaised"] == 2
    chains = ledger.chains(anomaly_type="SLO_BURN")
    assert sorted((c["outcome"] or "open") for c in chains) == \
        ["cleared", "open"]


def test_slo_burn_disabled_flip_still_clears_open_chains():
    clk = _Clock()
    reg, ledger, det = _burn_rig(clk,
                                 [Objective("shed", "shed", budget=0.01)])
    for _ in range(20):
        reg.record("shed", True)
    assert det.run_once() is not None
    # Flip the registry off under an open burn: the chain must reach a
    # terminal rather than leak open forever.
    reg._enabled = False
    assert det.run_once() is None
    assert det.state()["openBurns"] == []
    (chain,) = ledger.chains(anomaly_type="SLO_BURN")
    assert chain["outcome"] == "cleared"
    assert chain["phases"][-1]["via"] == "slo_disabled"


# ---- end to end through the real api -------------------------------------

_CAPS = StaticCapacityResolver({}, {Resource.CPU: 100.0, Resource.DISK: 1e7,
                                    Resource.NW_IN: 1e6, Resource.NW_OUT: 1e6})


def _partitions(brokers=(0, 1, 2, 3), topics=2, parts=6):
    out = {}
    for t in range(topics):
        for p in range(parts):
            reps = (brokers[0], brokers[1 + (t + p) % (len(brokers) - 1)])
            out[(f"t{t}", p)] = PartitionState(f"t{t}", p, reps, reps[0],
                                               isr=reps)
    return out


_G = "cruise_control_tpu.analyzer.goals"
_SHORT_CHAIN = [f"{_G}.RackAwareGoal", f"{_G}.ReplicaCapacityGoal",
                f"{_G}.ReplicaDistributionGoal"]


def _solo_api(extra, partitions):
    cfg = CruiseControlConfig({
        "goals": _SHORT_CHAIN,
        "hard.goals": [f"{_G}.RackAwareGoal", f"{_G}.ReplicaCapacityGoal"],
        "anomaly.detection.goals": _SHORT_CHAIN,
        "partition.metrics.window.ms": 1000,
        "num.partition.metrics.windows": 3,
        "min.valid.partition.ratio": 0.0,
        "max.solver.rounds": 30,
        "failed.brokers.file.path": "",
        "solver.partition.bucket.size": 0,
        "solver.broker.bucket.size": 0,
        **(extra or {})})
    backend = InMemoryAdminBackend(partitions.values())
    monitor = LoadMonitor(cfg, backend, samplers=[SyntheticSampler()],
                          capacity_resolver=_CAPS)
    cc = CruiseControl(cfg, backend, load_monitor=monitor,
                       executor=Executor(backend, synchronous=True))
    for k in range(1, 4):
        monitor.task_runner.run_sampling_once(end_ms=k * 1000)
    api = CruiseControlApi(cc)
    api._async_wait_s = 180
    return api, cc


def _scrubbed(body) -> str:
    """Canonical JSON minus the two wall-clock measurement fields (goal
    durations are machine noise with or without observation)."""
    b = json.loads(json.dumps(body))
    if isinstance(b.get("summary"), dict):
        b["summary"].pop("duration_s", None)
    for g in b.get("goalSummary") or []:
        g.pop("optimizationTimeMs", None)
    return json.dumps(b, sort_keys=True)


_SHAPES = {"narrow": dict(brokers=(0, 1, 2, 3), topics=2, parts=6),
           "wide": dict(brokers=tuple(range(8)), topics=2, parts=17)}


@pytest.mark.parametrize("shape", sorted(_SHAPES))
def test_observation_disabled_is_byte_identical(shape):
    """Off means off: journeys+SLO enabled vs disabled must produce the
    same proposals bytes (modulo the wall-clock duration fields) and the
    same loadgen schedule/response stability at two partition shapes."""
    bodies = {}
    sched_digests = {}
    for flag in (True, False):
        api, cc = _solo_api({"journey.enabled": flag, "slo.enabled": flag},
                            _partitions(**_SHAPES[shape]))
        try:
            status, body, _h = api.handle(
                "GET", "/kafkacruisecontrol/proposals")
            assert status == 200, body
            bodies[flag] = _scrubbed(body)
            assert cc.journeys.enabled is flag
            assert cc.slo.enabled is flag
            # A short pinned-seed loadgen run: the arrival schedule is a
            # pure function of the seed (never of the flags), and every
            # proposals spec must stay ONE byte pattern within the run.
            schedule = loadgen.generate_schedule(
                loadgen.mixed_profile(), seed=7, rate_rps=30.0,
                duration_s=0.4)
            report = loadgen.run_schedule(
                api, schedule, concurrency=4,
                journey_log=cc.journeys if flag else None)
            sched_digests[flag] = report.schedule_digest
            assert report.by_status.get(200, 0) >= 1
            for name, digs in report.digests.items():
                assert len(digs) == 1, (name, digs)
            assert (report.attribution is not None) is flag
            if not flag:
                assert cc.journeys.stats()["journeysOpened"] == 0
                assert cc.slo.events_recorded == 0
        finally:
            api.shutdown()
    assert bodies[True] == bodies[False]
    assert sched_digests[True] == sched_digests[False]


@pytest.fixture(scope="module")
def observed_api():
    api, cc = _solo_api({"journey.enabled": True, "slo.enabled": True},
                        _partitions())
    yield api, cc
    api.shutdown()


def test_journeys_attribute_a_real_solve(observed_api):
    api, cc = observed_api
    api.response_cache.invalidate()
    status, _body, _h = api.handle("GET", "/kafkacruisecontrol/proposals")
    assert status == 200
    entries = cc.journeys.entries(endpoint="PROPOSALS")
    assert entries, cc.journeys.stats()
    segs = {s["segment"] for s in entries[0]["segments"]}
    # The solve pipeline's named stages all land on the leader journey.
    assert {"admission", "cache_lookup", "queue_wait", "model_build",
            "solve", "render"} <= segs
    solve = next(s for s in entries[0]["segments"]
                 if s["segment"] == "solve")
    assert solve["seconds"] > 0.0
    table = segment_attribution(entries)
    assert table["attributed_fraction"] > 0.5


def test_journeys_endpoint_serves_the_ring(observed_api):
    api, _cc = observed_api
    api.handle("GET", "/kafkacruisecontrol/state")
    status, body, _h = api.handle("GET", "/kafkacruisecontrol/journeys",
                                  "endpoint=STATE&entries=5")
    assert status == 200
    assert body["journeysEnabled"] is True
    assert 1 <= body["numJourneys"] <= 5
    assert all(e["endpoint"] == "STATE" for e in body["journeys"])


def test_slo_endpoint_reports_objectives_and_detector(observed_api):
    api, _cc = observed_api
    api.handle("GET", "/kafkacruisecontrol/state")
    status, body, _h = api.handle("GET", "/kafkacruisecontrol/slo")
    assert status == 200
    assert body["sloEnabled"] is True
    assert body["eventsRecorded"] >= 1
    assert sorted(body["objectives"]) == ["error", "latency", "shed"]
    lat = body["objectives"]["latency"]
    assert set(lat["burnRate"]) == {f"{int(w)}s" for w in DEFAULT_WINDOWS_S}
    assert lat["budgetRemaining"] == 1.0
    assert body["burnDetector"] == {"openBurns": [], "burnsRaised": 0,
                                    "burnsCleared": 0}
    json.dumps(body, allow_nan=False)
    # ?objective= filters the table.
    _s, filtered, _h = api.handle("GET", "/kafkacruisecontrol/slo",
                                  "objective=shed")
    assert sorted(filtered["objectives"]) == ["shed"]


def test_loadgen_report_carries_segment_attribution(observed_api):
    api, cc = observed_api
    schedule = loadgen.generate_schedule(
        loadgen.mixed_profile(), seed=3, rate_rps=40.0, duration_s=0.5)
    assert schedule
    report = loadgen.run_schedule(api, schedule, concurrency=4,
                                  journey_log=cc.journeys)
    assert report.attribution is not None
    assert report.attribution["journeys"] >= len(schedule)
    assert report.attribution["attributed_fraction"] > 0.5
    assert report.to_dict()["attribution"] == report.attribution
    # Without a ring the report simply omits the table (old behavior).
    again = loadgen.run_schedule(api, schedule[:2], concurrency=2)
    assert again.attribution is None
    assert "attribution" not in again.to_dict()


def test_queue_wait_and_segment_histograms_are_emitted(observed_api):
    """serving_queue_wait_seconds{class=} lands at dequeue and every
    closed journey mirrors its segments into
    journey_segment_seconds{endpoint,segment}."""
    from cruise_control_tpu.utils.sensors import SENSORS
    api, _cc = observed_api
    api.response_cache.invalidate()
    assert api.handle("GET", "/kafkacruisecontrol/proposals")[0] == 200
    assert api.handle("GET", "/kafkacruisecontrol/state")[0] == 200
    with SENSORS._lock:
        series = list(SENSORS._histograms)
    queue_classes = {dict(labels).get("class")
                     for name, labels in series
                     if name == "serving_queue_wait_seconds"}
    assert {"SOLVER", "VIEWER"} <= queue_classes
    segments = {dict(labels).get("segment")
                for name, labels in series
                if name == "journey_segment_seconds"}
    assert {"admission", "cache_lookup", "solve", "render"} <= segments
    snap = SENSORS.histogram_snapshot(
        "serving_queue_wait_seconds", labels={"class": "SOLVER"})
    assert snap is not None and snap["count"] >= 1


# ---- twin parity ---------------------------------------------------------

def test_scenario_floor_strings_are_pinned():
    """The five verdict strings render byte-identically to the
    pre-registry ScenarioScore.slo_violations bodies."""
    assert scenario_floor_violations(
        unhealed=2, time_to_heal_p95_ticks=9, heal_ticks_floor=5,
        ticks_below_balancedness=3, balancedness_min=0.8,
        moves_per_simhour=125.0, moves_floor=100.0, dead_letters=1) == [
            "unhealed_faults=2",
            "time_to_heal_p95=9>5_ticks",
            "balancedness_below_0.8_for_3_ticks",
            "moves_per_simhour=125.0>100.0",
            "dead_letters=1"]
    assert scenario_floor_violations(
        unhealed=0, time_to_heal_p95_ticks=None, heal_ticks_floor=5,
        ticks_below_balancedness=0, balancedness_min=0.8,
        moves_per_simhour=50.0, moves_floor=100.0, dead_letters=0) == []


@pytest.mark.parametrize("seed", [0, 1])
def test_twin_score_verdicts_match_the_shared_renderer(seed):
    """ONE SLO definition for twin and production: the ScenarioScore
    floors render through utils.slo, byte-identical per seed."""
    from cruise_control_tpu.testing.simulator import run_scenario
    r = run_scenario("broker_loss_drift", seed=seed, ticks=12)
    score = r.score
    expected = scenario_floor_violations(
        unhealed=score.unhealed(),
        time_to_heal_p95_ticks=score.time_to_heal_p95_ticks(),
        heal_ticks_floor=score._slo_heal_ticks,
        ticks_below_balancedness=score.ticks_below_balancedness_slo,
        balancedness_min=score._slo_bal_min,
        moves_per_simhour=score.moves_per_simhour(),
        moves_floor=score._slo_moves_hr,
        dead_letters=score.dead_letters)
    assert score.slo_violations() == expected
    assert json.dumps(score.slo_violations()) == json.dumps(expected)
