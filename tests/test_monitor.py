"""Monitor ingestion: reporter serde → processor → samplers → LoadMonitor →
ClusterTensors (reference parity: CruiseControlMetricsProcessor,
MetricFetcherManager, KafkaSampleStore replay, LoadMonitor.clusterModel)."""

import numpy as np
import pytest

from cruise_control_tpu.common.broker_state import BrokerState
from cruise_control_tpu.common.resources import Resource
from cruise_control_tpu.config.cruise_control_config import CruiseControlConfig
from cruise_control_tpu.executor.admin import InMemoryAdminBackend, PartitionState
from cruise_control_tpu.metricdef.kafka_metric_def import CommonMetric as CM
from cruise_control_tpu.metricdef.raw_metric_type import RawMetricType as R
from cruise_control_tpu.model.tensors import broker_load
from cruise_control_tpu.monitor import (
    LoadMonitor, ModelCompletenessRequirements, StaticCapacityResolver,
)
from cruise_control_tpu.monitor.sampling import (
    CruiseControlMetricsProcessor, CruiseControlMetricsReporterSampler,
    FileSampleStore, InMemoryMetricsTransport, SyntheticSampler,
    default_partition_assignor,
)
from cruise_control_tpu.reporter.metrics import (
    broker_metric, deserialize, partition_metric, serialize, topic_metric,
)


def _partitions(n_topics=2, parts_per_topic=2, brokers=(0, 1, 2)):
    out = {}
    for t in range(n_topics):
        topic = f"t{t}"
        for p in range(parts_per_topic):
            leader = brokers[(t + p) % len(brokers)]
            replicas = (leader, brokers[(t + p + 1) % len(brokers)])
            out[(topic, p)] = PartitionState(topic, p, replicas, leader,
                                             isr=replicas)
    return out


def _report_interval(transport, partitions, time_ms, bytes_in_per_topic=100.0):
    """Emit a consistent raw-metric interval for every leader broker."""
    by_broker = {}
    for (topic, p), st in partitions.items():
        by_broker.setdefault(st.leader, set()).add(topic)
    for broker, topics in by_broker.items():
        n = len(topics)
        transport.produce_metric(broker_metric(R.BROKER_CPU_UTIL, time_ms, broker, 0.5))
        transport.produce_metric(broker_metric(R.ALL_TOPIC_BYTES_IN, time_ms,
                                               broker, bytes_in_per_topic * n))
        transport.produce_metric(broker_metric(R.ALL_TOPIC_BYTES_OUT, time_ms,
                                               broker, 2 * bytes_in_per_topic * n))
        transport.produce_metric(broker_metric(R.ALL_TOPIC_REPLICATION_BYTES_IN,
                                               time_ms, broker, 10.0))
        for topic in topics:
            transport.produce_metric(topic_metric(R.TOPIC_BYTES_IN, time_ms,
                                                  broker, topic, bytes_in_per_topic))
            transport.produce_metric(topic_metric(R.TOPIC_BYTES_OUT, time_ms,
                                                  broker, topic, 2 * bytes_in_per_topic))
        for (topic, p), st in partitions.items():
            if st.leader == broker:
                transport.produce_metric(partition_metric(
                    R.PARTITION_SIZE, time_ms, broker, topic, p, 5000.0))


def test_metric_serde_roundtrip():
    for m in [broker_metric(R.BROKER_CPU_UTIL, 123, 7, 0.25),
              topic_metric(R.TOPIC_BYTES_IN, 456, 1, "payments", 99.5),
              partition_metric(R.PARTITION_SIZE, 789, 2, "payments", 3, 1e6)]:
        assert deserialize(serialize(m)) == m


def test_processor_distributes_topic_rates_and_estimates_cpu():
    partitions = _partitions(n_topics=1, parts_per_topic=2, brokers=(0,))
    transport = InMemoryMetricsTransport()
    _report_interval(transport, partitions, 1000)
    raw = [deserialize(b) for b in transport.poll(0, 2000)]
    res = CruiseControlMetricsProcessor().process(raw, partitions, 1000)
    assert len(res.partition_samples) == 2
    assert res.skipped_partitions == 0
    # Equal sizes → even split of the topic's 100 B/s.
    for s in res.partition_samples:
        assert s.metric_value(CM.LEADER_BYTES_IN) == pytest.approx(50.0)
        assert s.metric_value(CM.DISK_USAGE) == pytest.approx(5000.0)
        assert 0.0 < s.metric_value(CM.CPU_USAGE) <= 0.5
    # Broker sample carries CPU + totals.
    (b,) = res.broker_samples
    assert b.metric_value("CPU_USAGE") == pytest.approx(0.5)
    assert b.metric_value("LEADER_BYTES_IN") == pytest.approx(100.0)


def test_partition_assignor_is_deterministic_and_complete():
    partitions = _partitions(n_topics=5, parts_per_topic=7)
    a = default_partition_assignor(partitions, 3)
    b = default_partition_assignor(partitions, 3)
    assert [sorted(x) for x in a] == [sorted(x) for x in b]
    assert sum(len(x) for x in a) == len(partitions)


def test_partition_assignor_is_stable_across_processes():
    """Topic→fetcher placement must survive restarts: the assignor hashes
    with crc32, NOT builtin hash() (which varies per process under
    PYTHONHASHSEED). Pinned against literal crc32 values so a regression
    back to hash() fails regardless of this process's seed."""
    import zlib

    partitions = _partitions(n_topics=6, parts_per_topic=2)
    buckets = default_partition_assignor(partitions, 4)
    for i, bucket in enumerate(buckets):
        for (topic, _part) in bucket:
            assert zlib.crc32(topic.encode("utf-8")) % 4 == i
    # Topic granularity holds: no topic is split across fetchers.
    seen: dict[str, int] = {}
    for i, bucket in enumerate(buckets):
        for (topic, _part) in bucket:
            assert seen.setdefault(topic, i) == i


def test_file_sample_store_roundtrip(tmp_path):
    store = FileSampleStore(str(tmp_path / "samples"))
    partitions = _partitions(n_topics=1, parts_per_topic=1, brokers=(0,))
    res = SyntheticSampler().get_samples(partitions, 0, 1000)
    store.store_samples(res)
    loaded = store.load_samples()
    assert loaded.partition_samples == res.partition_samples
    assert loaded.broker_samples == res.broker_samples


def _load_monitor(partitions, transport=None, store=None, interval_ms=1000,
                  extra_cfg=None):
    backend = InMemoryAdminBackend(partitions.values())
    cfg = CruiseControlConfig({
        "metric.sampling.interval.ms": interval_ms,
        "partition.metrics.window.ms": interval_ms,
        "broker.metrics.window.ms": interval_ms,
        "num.partition.metrics.windows": 3,
        "min.valid.partition.ratio": 0.5,
        **(extra_cfg or {}),
    })
    sampler = (CruiseControlMetricsReporterSampler(transport)
               if transport is not None else SyntheticSampler())
    caps = StaticCapacityResolver({}, {Resource.CPU: 100.0, Resource.DISK: 1e6,
                                       Resource.NW_IN: 1e5, Resource.NW_OUT: 1e5})
    return LoadMonitor(cfg, backend, samplers=[sampler], sample_store=store,
                       capacity_resolver=caps,
                       broker_racks={0: "r0", 1: "r1", 2: "r2"})


def test_load_monitor_builds_cluster_model_from_reporter_metrics():
    partitions = _partitions(n_topics=2, parts_per_topic=3)
    transport = InMemoryMetricsTransport()
    monitor = _load_monitor(partitions, transport)
    # Three sampling intervals → windows roll and stabilize.
    for k in range(1, 4):
        _report_interval(transport, partitions, k * 1000 - 500)
        monitor.task_runner.run_sampling_once(end_ms=k * 1000)
    state, meta = monitor.cluster_model(
        ModelCompletenessRequirements(min_valid_windows=1,
                                      min_monitored_partitions_percentage=0.5))
    assert state.num_brokers == 3
    assert sorted(meta.broker_ids) == [0, 1, 2]
    assert meta.rack_names == ["r0", "r1", "r2"]
    assert int(state.partition_mask.sum()) == len(partitions)
    # Every broker leads one partition per topic (the full 100 B/s topic
    # rate each → 200 leader NW_IN) and follows two partitions (replication
    # NW_IN ≈ leader rate → +200).
    loads = np.asarray(broker_load(state))
    np.testing.assert_allclose(loads[:, int(Resource.NW_IN)], 400.0, rtol=0.05)
    st = monitor.state()
    assert st.total_num_partitions == len(partitions)
    assert st.num_valid_windows >= 1
    assert st.monitored_partitions_percentage == pytest.approx(1.0)


def test_load_monitor_marks_dead_brokers():
    partitions = _partitions(n_topics=1, parts_per_topic=2)
    backend = InMemoryAdminBackend(partitions.values())
    backend.kill_broker(2)
    cfg = CruiseControlConfig({"partition.metrics.window.ms": 1000,
                               "num.partition.metrics.windows": 2,
                               "min.valid.partition.ratio": 0.0})
    monitor = LoadMonitor(cfg, backend, samplers=[SyntheticSampler()])
    monitor.task_runner.run_sampling_once(end_ms=1000)
    monitor.task_runner.run_sampling_once(end_ms=2000)
    state, meta = monitor.cluster_model(
        ModelCompletenessRequirements(1, 0.0))
    dead = np.asarray(state.broker_state) == int(BrokerState.DEAD)
    assert dead[meta.broker_ids.index(2)]


def test_sample_store_warm_restart(tmp_path):
    partitions = _partitions(n_topics=1, parts_per_topic=2, brokers=(0, 1, 2))
    store_dir = str(tmp_path / "warm")
    store = FileSampleStore(store_dir)
    m1 = _load_monitor(partitions, store=store)
    for k in range(1, 3):
        m1.task_runner.run_sampling_once(end_ms=k * 1000)
    n_before = m1.partition_aggregator.num_samples()
    assert n_before > 0

    # Fresh monitor over the same store: replay restores the windows.
    m2 = _load_monitor(partitions, store=FileSampleStore(store_dir))
    m2.start_up(block_on_load=True)
    try:
        assert m2.task_runner.samples_loaded > 0
        assert m2.partition_aggregator.num_samples() == n_before
    finally:
        m2.shutdown()


def test_train_fits_linear_cpu_model():
    """TRAIN flow: diverse (CPU, traffic) broker windows -> least-squares
    coefficients; the estimator switches to the trained model
    (LinearRegressionModelParameters.updateModelCoefficient idea)."""
    from cruise_control_tpu.metricdef.kafka_metric_def import KafkaMetricDef
    from cruise_control_tpu.monitor.sampling.samples import BrokerEntity

    partitions = _partitions(n_topics=1, parts_per_topic=2, brokers=(0, 1, 2))
    # The faithful defaults need 100 samples/bucket (MonitorConfig); this
    # fixture feeds 120 rows total, so relax the per-bucket requirement.
    monitor = _load_monitor(partitions, extra_cfg={
        "linear.regression.model.required.samples.per.bucket": 1})
    bdef = KafkaMetricDef.broker_metric_def()
    agg = monitor.broker_aggregator
    ids = {n: bdef.metric_info(n).id for n in
           ("CPU_USAGE", "LEADER_BYTES_IN", "LEADER_BYTES_OUT",
            "REPLICATION_BYTES_IN_RATE")}
    # Synthesize windows where cpu = 0.001*in + 0.0005*out exactly, with
    # rates spread wide so every CPU bucket gets hits.
    rng = np.random.default_rng(0)
    for w in range(40):
        for b in (0, 1, 2):
            row = np.zeros(bdef.num_metrics)
            bytes_in = float(rng.uniform(0, 900))
            bytes_out = float(rng.uniform(0, 400))
            row[ids["LEADER_BYTES_IN"]] = bytes_in
            row[ids["LEADER_BYTES_OUT"]] = bytes_out
            row[ids["CPU_USAGE"]] = 0.001 * bytes_in + 0.0005 * bytes_out
            agg.add_sample(BrokerEntity(b), w * 1000 + 500, row)
    result = monitor.train(0, 50_000)
    assert result["trained"], result
    c = result["coefficients"]
    assert c[0] == pytest.approx(0.001, rel=0.05)
    assert c[1] == pytest.approx(0.0005, rel=0.1)


def test_prometheus_http_get_against_local_server():
    """The stdlib Prometheus client speaks /api/v1/query for real: a tiny
    local HTTP server plays Prometheus (PrometheusAdapter.java parity)."""
    import json
    import threading
    from http.server import BaseHTTPRequestHandler, HTTPServer
    from urllib.parse import parse_qs, urlparse

    from cruise_control_tpu.monitor.sampling.sampler import (
        PrometheusMetricSampler, prometheus_http_get,
    )

    seen = {}

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):
            u = urlparse(self.path)
            q = parse_qs(u.query)
            seen["path"] = u.path
            seen["query"] = q.get("query", [""])[0]
            body = json.dumps({
                "status": "success",
                "data": {"result": [
                    {"metric": {"instance": "b1:7071", "topic": "t"},
                     "value": [q.get("time", ["0"])[0], "123.5"]}]}})
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.end_headers()
            self.wfile.write(body.encode())

        def log_message(self, *a):  # quiet
            pass

    srv = HTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        http_get = prometheus_http_get(
            f"http://127.0.0.1:{srv.server_address[1]}")
        rows = http_get("rate(kafka_server_bytes_in[1m])", 1234.0)
        assert seen["path"] == "/api/v1/query"
        assert "rate(" in seen["query"]
        assert rows == [({"instance": "b1:7071", "topic": "t"}, 123.5)]
        # from_endpoint wires the urllib client end to end: get_samples
        # consumes the local server's answers through the real path
        sampler = PrometheusMetricSampler.from_endpoint(
            f"http://127.0.0.1:{srv.server_address[1]}",
            broker_of_instance=lambda inst: 1 if inst.startswith("b1") else None)
        res = sampler.get_samples({}, 0, 2_000_000)
        assert res.broker_samples, "sampler must produce broker samples"
    finally:
        srv.shutdown()
        srv.server_close()
