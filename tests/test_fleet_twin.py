"""fleet_megabatch twin scenario (testing/fleet_twin.py): two drifting
simulated clusters sharing one bucket, precomputes megabatched through
one coalescing fleet, self-healing through the real loop."""

import pytest

from cruise_control_tpu.testing.fleet_twin import run_fleet_megabatch


@pytest.mark.slow  # ~9 s of twin ticks; the full-spec twin below and
# CI's fleet_megabatch matrix row cover the same machinery
def test_fleet_twin_megabatch_smoke():
    """Short horizon (one broker loss, twin-a's): batched solves really
    happen at occupancy 2, the loss heals through the real detector/
    executor machinery, and the combined SLO list is clean."""
    r = run_fleet_megabatch(seed=0, ticks=24)
    assert r["megabatch_batches"] > 0
    assert r["megabatch_last_occupancy"] == 2
    assert r["megabatch_avg_occupancy"] == 2.0
    assert r["unhealed_faults"] == 0
    assert r["events_applied"] == 1        # twin-b's loss is at tick 29
    assert r["slo_violations"] == []
    assert r["dead_letters"] == 0
    assert r["balancedness_final"] is not None


@pytest.mark.slow
def test_fleet_twin_deterministic():
    """Same seed => byte-identical record (assignments of BOTH twins,
    heal timings, scores) — the ClusterSimulator determinism contract
    extended across the shared clock, scheduler, and batched solves."""
    a = run_fleet_megabatch(seed=1, ticks=36)
    b = run_fleet_megabatch(seed=1, ticks=36)
    a.pop("wall_s")
    b.pop("wall_s")
    assert a == b
    assert a["megabatch_batches"] > 0
