"""Randomized property-style optimizer invariants.

Reference parity: analyzer/OptimizationVerifier.java:69-339 — the tier-2
pattern of SURVEY.md §4: run a goal chain over parameterized random
clusters and assert INVARIANTS (hard goals satisfied, dead brokers
drained, stats never regress, exclusions honored), never golden outputs.
Mirrors RandomClusterTest / RandomGoalTest / RandomSelfHealingTest /
ExcludedTopicsTest across UNIFORM/LINEAR/EXPONENTIAL load distributions
and multiple seeds.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from cruise_control_tpu.analyzer.constraint import (
    BalancingConstraint, OptimizationOptions,
)
from cruise_control_tpu.analyzer.optimizer import (
    GoalOptimizer, goals_by_priority,
)
from cruise_control_tpu.common.broker_state import BrokerState
from cruise_control_tpu.config.cruise_control_config import CruiseControlConfig
from cruise_control_tpu.model import fixtures
from cruise_control_tpu.model.fixtures import Dist
from cruise_control_tpu.model.tensors import (
    broker_load, broker_replica_counts, offline_replicas, replica_exists,
    set_broker_state,
)

CFG = CruiseControlConfig({"max.solver.rounds": 200,
                           "failed.brokers.file.path": ""})
CHAIN = ["RackAwareGoal", "ReplicaCapacityGoal", "DiskCapacityGoal",
         "NetworkOutboundCapacityGoal", "ReplicaDistributionGoal",
         "NetworkOutboundUsageDistributionGoal",
         "TopicReplicaDistributionGoal", "LeaderReplicaDistributionGoal"]


def _cluster(dist: Dist, seed: int):
    return fixtures.random_cluster(
        num_brokers=16, num_topics=8, num_partitions=192, rf=3, num_racks=4,
        dist=dist, seed=seed, skew_to_first=2.0, target_utilization=0.5)


def _assert_consistent(state, meta):
    """Structural sanity after any optimization (LoadConsistencyTest role):
    every partition keeps its replica count, no duplicate brokers within a
    partition, leader slot holds a live replica."""
    a = np.asarray(state.assignment)
    mask = np.asarray(state.partition_mask)
    leader = np.asarray(state.leader_slot)
    for p in np.nonzero(mask)[0]:
        replicas = a[p][a[p] >= 0]
        assert len(replicas) == len(set(replicas)), f"dup broker, p={p}"
        assert a[p, leader[p]] >= 0, f"leader on empty slot, p={p}"


@pytest.mark.parametrize("dist", [Dist.UNIFORM, Dist.LINEAR,
                                  Dist.EXPONENTIAL])
@pytest.mark.parametrize("seed", [0, 7])
def test_random_cluster_hard_goals_and_no_regression(dist, seed):
    """GOAL_VIOLATION + REGRESSION verifications: on every distribution and
    seed, all hard goals end satisfied, balancedness never decreases, and
    replica-count structure stays consistent."""
    state, meta = _cluster(dist, seed)
    rf_before = np.asarray(replica_exists(state)).sum()
    opt = GoalOptimizer(CFG)
    final, result = opt.optimizations(state, meta,
                                      goals=goals_by_priority(CFG, CHAIN))
    hard = {r.name for r in result.goal_results if r.is_hard}
    violated = set(result.violated_goals_after)
    assert not (hard & violated), (dist, seed, hard & violated)
    assert result.balancedness_after >= result.balancedness_before - 1e-6
    assert np.asarray(replica_exists(final)).sum() == rf_before
    _assert_consistent(final, meta)


@pytest.mark.parametrize("dist", [Dist.UNIFORM, Dist.EXPONENTIAL])
def test_random_self_healing_drains_dead_brokers(dist):
    """BROKEN_BROKERS verification (RandomSelfHealingTest): after killing
    brokers, optimization leaves ZERO replicas on them and hard goals hold
    on the survivors."""
    state, meta = _cluster(dist, seed=3)
    dead = [13, 14, 15]
    state = set_broker_state(state, jnp.asarray(dead), BrokerState.DEAD)
    assert int(offline_replicas(state).sum()) > 0
    opt = GoalOptimizer(CFG)
    final, result = opt.optimizations(state, meta,
                                      goals=goals_by_priority(CFG, CHAIN))
    counts = np.asarray(broker_replica_counts(final))
    assert counts[dead].sum() == 0, counts[dead]
    assert int(offline_replicas(final).sum()) == 0
    hard = {r.name for r in result.goal_results if r.is_hard}
    assert not (hard & set(result.violated_goals_after))
    _assert_consistent(final, meta)


def test_random_new_broker_gating():
    """NEW_BROKERS verification (RandomClusterNewBrokerTest): brokers in NEW
    state are the only ones gaining replicas during distribution passes."""
    state, meta = _cluster(Dist.LINEAR, seed=11)
    new = [14, 15]
    state = set_broker_state(state, jnp.asarray(new), BrokerState.NEW)
    before = np.asarray(broker_replica_counts(state))
    opt = GoalOptimizer(CFG)
    final, _res = opt.optimizations(
        state, meta, goals=goals_by_priority(
            CFG, ["ReplicaDistributionGoal",
                  "NetworkOutboundUsageDistributionGoal"]))
    after = np.asarray(broker_replica_counts(final))
    gained = np.nonzero(after > before)[0]
    assert set(gained.tolist()) <= set(new), gained


def test_random_excluded_brokers_for_replica_move_gain_nothing():
    """ExcludedBrokersForReplicaMoveTest: brokers excluded for replica
    moves never GAIN a replica during a full chain run (they may shed —
    requireLessLoad includes excluded brokers,
    ResourceDistributionGoal.java:387)."""
    state, meta = _cluster(Dist.EXPONENTIAL, seed=3)
    excluded = [2, 9]
    excluded_ids = tuple(meta.broker_ids[b] for b in excluded)
    before = np.asarray(state.assignment).copy()
    opt = GoalOptimizer(CFG)
    final, _res = opt.optimizations(
        state, meta, goals=goals_by_priority(CFG, CHAIN),
        options=OptimizationOptions(
            excluded_brokers_for_replica_move=excluded_ids))
    after = np.asarray(final.assignment)
    for b in excluded:
        hosted_before = set(map(tuple, np.argwhere(before == b)))
        hosted_after = set(map(tuple, np.argwhere(after == b)))
        gained = {p for p, _s in hosted_after} - {p for p, _s in hosted_before}
        assert not gained, f"excluded broker {b} gained partitions {gained}"
    _assert_consistent(final, meta)


def test_random_excluded_brokers_for_leadership_gain_no_leaders():
    """ExcludedBrokersForLeadershipTest: brokers excluded for leadership
    never end up leading a partition they did not already lead."""
    state, meta = _cluster(Dist.LINEAR, seed=9)
    excluded = [0, 5]
    excluded_ids = tuple(meta.broker_ids[b] for b in excluded)
    a0 = np.asarray(state.assignment)
    l0 = np.asarray(state.leader_slot)
    leaders_before = {p: a0[p, l0[p]] for p in range(a0.shape[0])}
    opt = GoalOptimizer(CFG)
    final, _res = opt.optimizations(
        state, meta, goals=goals_by_priority(CFG, CHAIN),
        options=OptimizationOptions(
            excluded_brokers_for_leadership=excluded_ids))
    a1 = np.asarray(final.assignment)
    l1 = np.asarray(final.leader_slot)
    mask = np.asarray(final.partition_mask)
    for p in np.nonzero(mask)[0]:
        leader = a1[p, l1[p]]
        if leader in excluded:
            assert leaders_before[p] == leader, \
                f"excluded broker {leader} GAINED leadership of {p}"
    _assert_consistent(final, meta)


def test_random_excluded_topics_never_move():
    """ExcludedTopicsTest: replicas of excluded topics keep their exact
    placement through a full chain run."""
    state, meta = _cluster(Dist.EXPONENTIAL, seed=5)
    excluded = meta.topic_names[0]
    topic_idx = 0
    rows = np.asarray(state.topic) == topic_idx
    before = np.asarray(state.assignment)[rows].copy()
    opt = GoalOptimizer(CFG)
    final, _res = opt.optimizations(
        state, meta, goals=goals_by_priority(CFG, CHAIN),
        options=OptimizationOptions(excluded_topics=(excluded,)))
    after = np.asarray(final.assignment)[rows]
    np.testing.assert_array_equal(after, before)


@pytest.mark.parametrize("order_seed", [1, 2])
def test_random_goal_order_keeps_hard_goals(order_seed):
    """RandomGoalTest: shuffling the SOFT goal order never breaks hard
    goals (the lexicographic acceptance stack is order-independent for
    hard-goal preservation)."""
    rng = np.random.default_rng(order_seed)
    hard = CHAIN[:4]
    soft = CHAIN[4:]
    rng.shuffle(soft)
    state, meta = _cluster(Dist.UNIFORM, seed=2)
    opt = GoalOptimizer(CFG)
    final, result = opt.optimizations(
        state, meta, goals=goals_by_priority(CFG, hard + soft))
    hard_names = {r.name for r in result.goal_results if r.is_hard}
    assert not (hard_names & set(result.violated_goals_after))
    _assert_consistent(final, meta)


def test_random_cluster_load_conserved():
    """Total cluster load is invariant under optimization (moves relocate
    load, never create or destroy it)."""
    state, meta = _cluster(Dist.EXPONENTIAL, seed=9)
    total_before = np.asarray(broker_load(state)).sum(axis=0)
    opt = GoalOptimizer(CFG)
    final, _res = opt.optimizations(state, meta,
                                    goals=goals_by_priority(CFG, CHAIN))
    total_after = np.asarray(broker_load(final)).sum(axis=0)
    np.testing.assert_allclose(total_after, total_before, rtol=1e-4)


@pytest.mark.parametrize("dist", [Dist.UNIFORM, Dist.EXPONENTIAL])
def test_random_bounded_dispatch_equivalence(dist):
    """The bounded-dispatch production path (the large-cluster watchdog
    mitigation) walks the identical trajectory to the fused chain on
    random clusters — exact same proposals and balancedness."""
    state, meta = _cluster(dist, seed=3)
    fused = GoalOptimizer(CFG)
    bounded = GoalOptimizer(CruiseControlConfig({
        "max.solver.rounds": 200, "failed.brokers.file.path": "",
        "solver.fused.chain.max.brokers": "4",
        "solver.dispatch.max.rounds": "5"}))
    _f, rf_ = fused.optimizations(state, meta,
                                  goals=goals_by_priority(CFG, CHAIN))
    _b, rb_ = bounded.optimizations(state, meta,
                                    goals=goals_by_priority(CFG, CHAIN))
    assert sorted((p.topic, p.partition, p.new_replicas, p.new_leader)
                  for p in rb_.proposals) == \
        sorted((p.topic, p.partition, p.new_replicas, p.new_leader)
               for p in rf_.proposals)
    assert rb_.balancedness_after == pytest.approx(rf_.balancedness_after)
