"""Megabatch fleet solver (round 14): whole buckets of clusters through
one donated device program.

The load-bearing contract (same discipline as PR 5's bounded==fused
pins): a megabatch solve of N clusters is BYTE-IDENTICAL per cluster to
N serial solves, at any occupancy — pad slots are inert, a converged
cluster is frozen by its early-exit mask while batchmates keep
searching, and occupancy never compiles a new program (one compiled
program per bucket shape, XLA-compile-counter asserted)."""

import jax.numpy as jnp
import numpy as np
import pytest

from cruise_control_tpu.analyzer.chain import (
    AdaptiveDispatch, DispatchStats, MegastepConfig, inert_state_like,
    megabatch_goal_stats, megabatch_optimize_rounds,
    optimize_goal_in_chain, optimize_goal_in_chain_megabatch,
    run_megabatch_pass, stack_states, unstack_state,
)
from cruise_control_tpu.analyzer.constraint import BalancingConstraint
from cruise_control_tpu.analyzer.goals import (
    NetworkOutboundUsageDistributionGoal, PreferredLeaderElectionGoal,
    RackAwareGoal, ReplicaCapacityGoal, ReplicaDistributionGoal,
)
from cruise_control_tpu.analyzer.optimizer import GoalOptimizer
from cruise_control_tpu.analyzer.search import (
    ExclusionMasks, OptimizationFailureError, SearchConfig,
)
from cruise_control_tpu.model.fixtures import random_cluster

# Same chain / grid / shapes as tests/test_megastep.py, so the serial
# reference kernels are already compiled when both files run in one
# session — the megabatch pins then only pay the batched compiles.
CHAIN = (RackAwareGoal(), ReplicaCapacityGoal(),
         NetworkOutboundUsageDistributionGoal(), ReplicaDistributionGoal(),
         PreferredLeaderElectionGoal())
CFG = SearchConfig(num_sources=32, num_dests=8, moves_per_round=32,
                   max_rounds=60)
MEGA = MegastepConfig(donate=True, async_readback=True, deficit_moves_cap=0)
WIDTH = 4
SEEDS = (3, 5, 7, 11)


def _cluster(seed, partition_bucket=32):
    return random_cluster(num_brokers=12, num_topics=6, num_partitions=96,
                          rf=2, num_racks=3, seed=seed, skew_to_first=2.0,
                          partition_bucket=partition_bucket)


def _run_serial(state, meta, k=8):
    masks = ExclusionMasks()
    dispatch = AdaptiveDispatch(k, 0.0)
    infos = []
    for i in range(len(CHAIN)):
        state, info = optimize_goal_in_chain(
            state, CHAIN, i, BalancingConstraint(), CFG, meta.num_topics,
            masks, dispatch_rounds=k, dispatch=dispatch, megastep=MEGA,
            donate_input=bool(infos) and any(x["rounds"] > 0 for x in infos))
        infos.append(info)
    return state, infos


def _run_megabatch(states, num_topics, cluster_mask, k=8):
    """Drive the whole chain through the batched per-goal driver (the
    optimizer's megabatch loop, minus the result assembly)."""
    batched = stack_states(states)
    masks = ExclusionMasks()
    dispatch = AdaptiveDispatch(k, 0.0)
    cluster_mask = np.asarray(cluster_mask, dtype=bool)
    dead = np.zeros(len(states), dtype=bool)
    infos_per_goal = []
    donate_input = False
    for i in range(len(CHAIN)):
        batched, infos = optimize_goal_in_chain_megabatch(
            batched, CHAIN, i, BalancingConstraint(), CFG, num_topics,
            masks, cluster_mask & ~dead, dispatch_rounds=k,
            dispatch=dispatch, megastep=MEGA, donate_input=donate_input)
        donate_input = donate_input or any(x["rounds"] > 0 for x in infos)
        for b, info in enumerate(infos):
            if "error" in info:
                dead[b] = True
        infos_per_goal.append(infos)
    return batched, infos_per_goal


# The two pinned bucket shapes (32 keeps P=96 unpadded; 128 pads the
# partition axis) x the two pinned occupancies {full, 1-of-4 padded}.
@pytest.mark.parametrize("bucket", [32, 128])
def test_megabatch_parity_pin_and_one_program_per_shape(bucket):
    clusters = [_cluster(s, partition_bucket=bucket) for s in SEEDS]
    serial = [_run_serial(st, meta) for st, meta in clusters]
    num_topics = clusters[0][1].num_topics
    cache0 = megabatch_optimize_rounds._cache_size()

    # Full occupancy: 4 real clusters.
    full, infos_full = _run_megabatch([st for st, _m in clusters],
                                      num_topics, [True] * WIDTH)
    # 1-of-4: one real cluster + three inert pad slots, SAME program.
    lone = [clusters[0][0]] + [inert_state_like(clusters[0][0])] * 3
    padded, infos_padded = _run_megabatch(lone, num_topics,
                                          [True, False, False, False])
    # One compiled batched move program serves both occupancies of this
    # bucket shape (occupancy is traced, never a recompile).
    assert megabatch_optimize_rounds._cache_size() - cache0 == 1

    for b in range(WIDTH):
        ref_state, ref_infos = serial[b]
        got = unstack_state(full, b)
        np.testing.assert_array_equal(np.asarray(ref_state.assignment),
                                      np.asarray(got.assignment))
        np.testing.assert_array_equal(np.asarray(ref_state.leader_slot),
                                      np.asarray(got.leader_slot))
        for gi, a in enumerate(ref_infos):
            m = infos_full[gi][b]
            assert a["rounds"] == m["rounds"], (b, gi)
            assert a["moves_applied"] == m["moves_applied"], (b, gi)
            assert a["swaps_applied"] == m["swaps_applied"], (b, gi)
            assert a["succeeded"] == m["succeeded"], (b, gi)
            assert abs(a["residual_violation"]
                       - m["residual_violation"]) < 1e-5

    # The lone real cluster in the padded batch walks the same bytes.
    ref_state, ref_infos = serial[0]
    got = unstack_state(padded, 0)
    np.testing.assert_array_equal(np.asarray(ref_state.assignment),
                                  np.asarray(got.assignment))
    np.testing.assert_array_equal(np.asarray(ref_state.leader_slot),
                                  np.asarray(got.leader_slot))
    for gi, a in enumerate(ref_infos):
        assert a["rounds"] == infos_padded[gi][0]["rounds"], gi

    # Inert pad slots: byte-frozen, zero rounds, zero moves.
    inert = inert_state_like(clusters[0][0])
    for b in (1, 2, 3):
        got = unstack_state(padded, b)
        np.testing.assert_array_equal(np.asarray(inert.assignment),
                                      np.asarray(got.assignment))
        for gi in range(len(CHAIN)):
            assert infos_padded[gi][b]["rounds"] == 0
            assert infos_padded[gi][b]["moves_applied"] == 0


def test_early_exit_mask_freezes_converged_cluster():
    """A converged cluster in a live batch runs exactly one zero-apply
    round and freezes (per-cluster early-exit), while its skewed
    batchmate keeps searching — the batched analogue of the serial
    on-device early-exit pin."""
    (st_a, meta), (st_b, _mb) = _cluster(3), _cluster(7)
    converged, _ = _run_serial(st_a, meta)
    batched = stack_states([converged, st_b])
    out = megabatch_optimize_rounds(
        batched, jnp.asarray([True, True]), jnp.int32(3),
        jnp.asarray([j < 3 for j in range(len(CHAIN))]), CHAIN,
        BalancingConstraint(), CFG, meta.num_topics, ExclusionMasks(),
        jnp.int32(50))
    new_states, applied, rounds, active = out[:4]
    rounds = np.asarray(rounds)
    # Already-optimized cluster A: PreferredLeader etc. of goal 3 —
    # converged means its first round applies nothing and exits.
    assert rounds[0] >= 1
    np.testing.assert_array_equal(
        np.asarray(unstack_state(new_states, 0).assignment),
        np.asarray(converged.assignment))
    assert not bool(np.asarray(active)[0])


def test_pump_speculative_dispatch_runs_zero_rounds():
    """With async readback the pump enqueues one dispatch past
    convergence; every cluster enters it inactive, so it runs ZERO
    rounds (cheaper than the serial speculative zero-apply round) and is
    recorded speculative without contributing rounds or moves."""
    st, meta = _cluster(3)
    final, _ = _run_serial(st, meta)
    batched = stack_states([final, final])
    idx = jnp.int32(len(CHAIN) - 1)
    prior = jnp.asarray([j < len(CHAIN) - 1 for j in range(len(CHAIN))])

    def enqueue(states, active, budget):
        out = megabatch_optimize_rounds(
            states, active, idx, prior, CHAIN, BalancingConstraint(), CFG,
            meta.num_topics, ExclusionMasks(), jnp.int32(budget))
        states, applied, rounds, act = out[:4]
        return states, act, applied, rounds, False, None

    physical = DispatchStats()
    per_cluster = [DispatchStats(), DispatchStats()]
    controller = AdaptiveDispatch(8, 0.0)
    _st, active, applied, rounds = run_megabatch_pass(
        enqueue, batched, jnp.asarray([True, True]), CFG.max_rounds,
        controller, async_readback=True, stats=per_cluster,
        physical_stats=physical)
    assert not active.any()
    # One real dispatch (the terminal zero-apply round per cluster) plus
    # the speculative zero-round drain.
    assert physical.speculative == 1
    assert physical.dispatch_count == 2
    assert list(rounds) == [1, 1]
    assert list(applied) == [0, 0]
    for s in per_cluster:
        assert s.speculative == 0 and s.rounds_per_dispatch == [1]


def test_optimizer_megabatch_matches_serial_results():
    """Integration parity at the GoalOptimizer level: final states,
    balancedness, violated sets, and proposal lists all match serial
    optimizations(); per-cluster dispatch stats split out of the batched
    readback."""
    from cruise_control_tpu.config.cruise_control_config import (
        CruiseControlConfig,
    )
    cfg = CruiseControlConfig({"max.solver.rounds": 60})
    opt = GoalOptimizer(cfg)
    items = []
    for seed in (3, 7):
        st, meta = _cluster(seed)
        items.append((st, meta, f"c{seed}"))
    serial = [opt.optimizations(st, meta, goals=list(CHAIN))
              for st, meta, _ in items]
    out = opt.optimizations_megabatch(items, goals=list(CHAIN), width=WIDTH)
    for b, ((s_final, s_res), r) in enumerate(zip(serial, out)):
        assert not isinstance(r, Exception), r
        m_final, m_res = r
        np.testing.assert_array_equal(np.asarray(s_final.assignment),
                                      np.asarray(m_final.assignment))
        assert s_res.balancedness_after == m_res.balancedness_after
        assert s_res.violated_goals_after == m_res.violated_goals_after
        assert [(p.topic, p.partition, p.new_replicas)
                for p in s_res.proposals] == \
            [(p.topic, p.partition, p.new_replicas)
             for p in m_res.proposals]
    split = opt.last_megabatch_cluster_stats()
    assert set(split) == {"c3", "c7"}
    assert all(v["dispatch_count"] > 0 for v in split.values())


def test_per_cluster_error_containment():
    """A hard-goal failure on one cluster fails exactly that cluster's
    slot (with the exception a serial solve would raise) and leaves its
    batchmate's solve intact."""
    from cruise_control_tpu.config.cruise_control_config import (
        CruiseControlConfig,
    )
    cfg = CruiseControlConfig({"max.solver.rounds": 60})
    opt = GoalOptimizer(cfg)
    healthy_st, healthy_meta = _cluster(3)
    # One rack + rf=2: RackAwareGoal (hard) is structurally unfixable.
    poisoned_st, poisoned_meta = random_cluster(
        num_brokers=12, num_topics=6, num_partitions=96, rf=2, num_racks=1,
        seed=5, skew_to_first=2.0, partition_bucket=32)
    out = opt.optimizations_megabatch(
        [(poisoned_st, poisoned_meta, "bad"),
         (healthy_st, healthy_meta, "good")],
        goals=list(CHAIN), width=WIDTH)
    assert isinstance(out[0], OptimizationFailureError)
    final, res = out[1]
    ref_final, ref_res = opt.optimizations(healthy_st, healthy_meta,
                                           goals=list(CHAIN))
    np.testing.assert_array_equal(np.asarray(ref_final.assignment),
                                  np.asarray(final.assignment))
    assert ref_res.violated_goals_after == res.violated_goals_after


def test_megabatch_precondition_mismatch_raises():
    st1, meta1 = _cluster(3)
    st2, meta2 = _cluster(7, partition_bucket=128)
    opt = GoalOptimizer()
    with pytest.raises(ValueError, match="bucket shape"):
        opt.optimizations_megabatch([(st1, meta1, "a"), (st2, meta2, "b")],
                                    goals=list(CHAIN))
    with pytest.raises(ValueError, match="fast_mode"):
        from cruise_control_tpu.analyzer.constraint import (
            OptimizationOptions,
        )
        opt.optimizations_megabatch(
            [(st1, meta1, "a")], goals=list(CHAIN),
            options=OptimizationOptions(fast_mode=True))


def test_padded_megabatch_with_exclusion_masks():
    """Regression: a PARTIALLY-FILLED batch with a non-None exclusion
    mask must pad the stacked mask axis alongside the inert cluster
    slots (review finding: masks stacked at occupancy n while states
    padded to width c crashed vmap with an axis-size mismatch) — and
    stay byte-identical to the serial solve under the same options."""
    from cruise_control_tpu.analyzer.constraint import OptimizationOptions
    from cruise_control_tpu.config.cruise_control_config import (
        CruiseControlConfig,
    )
    cfg = CruiseControlConfig({"max.solver.rounds": 60})
    opt = GoalOptimizer(cfg)
    st, meta = _cluster(3)
    options = OptimizationOptions(excluded_topics=(meta.topic_names[0],))
    out = opt.optimizations_megabatch([(st, meta, "only")],
                                      goals=list(CHAIN), options=options,
                                      width=WIDTH)
    assert not isinstance(out[0], Exception), out[0]
    m_final, m_res = out[0]
    s_final, s_res = opt.optimizations(st, meta, goals=list(CHAIN),
                                       options=options)
    np.testing.assert_array_equal(np.asarray(s_final.assignment),
                                  np.asarray(m_final.assignment))
    assert s_res.violated_goals_after == m_res.violated_goals_after


def test_per_item_options_mixed_batch_parity():
    """Round 15: items may carry their OWN options (the fix path's and
    the futures engine's per-cluster exclusion sets). A mixed batch —
    one cluster excluding a topic and brokers, one excluding nothing —
    normalizes mask presence (inert all-False fills) and stays
    byte-identical per cluster to serial solves under the same
    options."""
    from cruise_control_tpu.analyzer.constraint import OptimizationOptions
    from cruise_control_tpu.config.cruise_control_config import (
        CruiseControlConfig,
    )
    cfg = CruiseControlConfig({"max.solver.rounds": 60})
    opt = GoalOptimizer(cfg)
    st_a, meta_a = _cluster(3)
    st_b, meta_b = _cluster(7)
    opts_a = OptimizationOptions(
        excluded_topics=(meta_a.topic_names[0],),
        excluded_brokers_for_replica_move=(meta_a.broker_ids[0],))
    opts_b = OptimizationOptions()
    out = opt.optimizations_megabatch(
        [(st_a, meta_a, "a", opts_a), (st_b, meta_b, "b", opts_b)],
        goals=list(CHAIN), width=WIDTH)
    for (st, meta, options), r in zip(
            [(st_a, meta_a, opts_a), (st_b, meta_b, opts_b)], out):
        assert not isinstance(r, Exception), r
        m_final, m_res = r
        s_final, s_res = opt.optimizations(st, meta, goals=list(CHAIN),
                                           options=options)
        np.testing.assert_array_equal(np.asarray(s_final.assignment),
                                      np.asarray(m_final.assignment))
        assert s_res.violated_goals_after == m_res.violated_goals_after
        assert [(p.topic, p.partition, p.new_replicas)
                for p in s_res.proposals] == \
            [(p.topic, p.partition, p.new_replicas)
             for p in m_res.proposals]
    with pytest.raises(ValueError, match="fast_mode"):
        from cruise_control_tpu.analyzer.constraint import (
            OptimizationOptions as OO,
        )
        opt.optimizations_megabatch(
            [(st_a, meta_a, "a", OO(fast_mode=True))], goals=list(CHAIN))


def test_uniform_mask_presence_normalization():
    opt = GoalOptimizer()
    masked = ExclusionMasks(excluded_topics=jnp.ones(4, bool))
    bare = ExclusionMasks()
    out = opt._uniform_mask_presence([masked, bare])
    assert out[0].excluded_topics is masked.excluded_topics
    assert out[1].excluded_topics.shape == (4,)
    assert not bool(np.asarray(out[1].excluded_topics).any())
    assert out[1].excluded_replica_move_brokers is None
    # All-bare lists pass through untouched.
    bares = [ExclusionMasks(), ExclusionMasks()]
    assert opt._uniform_mask_presence(bares) == bares


def test_stack_masks_uniformity():
    opt = GoalOptimizer()
    with pytest.raises(ValueError, match="uniform"):
        opt._stack_masks([
            ExclusionMasks(excluded_topics=jnp.zeros(4, bool)),
            ExclusionMasks()])
    stacked = opt._stack_masks([
        ExclusionMasks(excluded_topics=jnp.zeros(4, bool)),
        ExclusionMasks(excluded_topics=jnp.ones(4, bool))])
    assert stacked.excluded_topics.shape == (2, 4)
    assert stacked.excluded_replica_move_brokers is None


def test_inert_state_generates_no_work():
    st, meta = _cluster(3)
    inert = inert_state_like(st)
    batched = stack_states([inert, inert])
    viol, _obj, off = megabatch_goal_stats(
        batched, jnp.int32(0), CHAIN, BalancingConstraint(),
        meta.num_topics, ExclusionMasks())
    assert float(np.asarray(viol).sum()) == 0.0
    assert int(np.asarray(off).sum()) == 0
