"""CCSA004 fixture: a miner-shaped module that derives candidate seeds
from the wall clock and mutation picks from the global ``random`` state
(tests lint this file under the spoofed
cruise_control_tpu/redteam/miner.py path — the round-22 mining sweep is
a pure function of the sweep seed and the committed frontier JSON is
byte-identical per seed, so any inline clock/random call silently forks
the regression frontier; the wall budget rides the caller-injected
``clock`` callable only)."""

import random
import time


def bad_candidate_seed() -> float:
    return time.time()                   # finding: wall clock inline


def bad_mutation_pick() -> float:
    return random.random()               # finding: global random state


def injected_budget(clock=time.monotonic) -> float:
    return clock()                       # clean: reference is the seam


def timed_sweep() -> float:
    # ccsa: ok[CCSA004] fixture: observability-only harness wall time,
    # never enters the frontier JSON or any digest
    return time.perf_counter()
