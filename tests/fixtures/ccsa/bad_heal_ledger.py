"""CCSA004 + CCSA007 fixture: a heal-ledger-shaped journal with a
wall-clock leak and an unlocked module-level chain ring (tests lint this
file under a spoofed cruise_control_tpu/utils/heal_ledger.py path — the
round-16 ledger sits under the same injectable-clock determinism
contract as the twin, and its ring mutations must hold the lock)."""

import threading
import time

_CHAINS: list = []
_LOCK = threading.Lock()


def bad_stamp() -> int:
    return int(time.time() * 1000)       # finding: wall clock inline


def injected_stamp(clock=time.time) -> int:
    return int(clock() * 1000)           # clean: reference is the seam


def bad_open(chain) -> None:
    _CHAINS.append(chain)                # finding: unlocked ring mutation


def good_open(chain) -> None:
    with _LOCK:
        _CHAINS.append(chain)            # clean: lock-guarded


def tolerated_probe(chain) -> None:
    # ccsa: ok[CCSA007] fixture: single-writer test harness by contract
    _CHAINS.append(chain)


def timed_probe() -> float:
    # ccsa: ok[CCSA004] fixture: observability-only timer, never enters
    # a chain's phase stamps
    return time.perf_counter()
