"""CCSA004 fixture: a loadgen-shaped module that derives arrival gaps
from the wall clock and endpoint picks from the global ``random`` state
(tests lint this file under the spoofed
cruise_control_tpu/serving/loadgen.py path — the round-20 load-test
schedule is a pure function of the seed and its digest is pinned in
bench_baseline.json, so any inline clock/random call desyncs replays;
latency measurement rides the injected ``monotonic`` seam)."""

import random
import time


def bad_arrival_gap() -> float:
    return time.time()                   # finding: wall clock inline


def bad_endpoint_pick() -> float:
    return random.random()               # finding: global random state


def injected_latency(monotonic=time.monotonic) -> float:
    return monotonic()                   # clean: reference is the seam


def timed_run() -> float:
    # ccsa: ok[CCSA004] fixture: observability-only harness wall time,
    # never enters the schedule or any digest
    return time.perf_counter()
