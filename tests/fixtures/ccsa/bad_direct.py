"""CCSA001/CCSA002 fixture for the direct-assignment transport kernels
(analyzer/direct.py, round 17): a donated direct kernel is a pump-file
region (detected structurally via its donate_argnums decorator), so a
host sync traced into it is a per-compile constant — the
silent-wrong-answer class — and its donation set must stay exactly the
strip_mutable pair. Scanned under the SPOOFED rel path
``cruise_control_tpu/analyzer/direct.py`` by tests/test_ccsa.py; under
its own path the file is silent for CCSA001 (path-scoped rule)."""

from functools import partial

import jax


@partial(jax.jit, donate_argnums=(0, 1))
def direct_transport_rounds_donated(assignment, leader_slot, rest, plan):
    sweeps = float(plan)            # finding: CCSA001 host sync in region
    moves = plan.tolist()           # finding: CCSA001 host sync in region
    # ccsa: ok[CCSA001] fixture: annotated deliberate readback
    budget = int(plan)
    return assignment, leader_slot, sweeps, moves, budget


@partial(jax.jit, donate_argnums=(0, 1, 2))
def direct_donates_topology(assignment, leader_slot, rest):
    # finding: CCSA002 — `rest` is refresh-cache-shared topology
    return assignment, leader_slot, rest


def run_direct_pass(state, plan):
    # NOT a region (plain host driver): a synchronous readback after a
    # single dispatch is the documented contract — silent here.
    return int(plan)
