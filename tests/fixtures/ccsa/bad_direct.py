"""CCSA001/CCSA002 fixture for the direct-assignment transport kernels
(analyzer/direct.py, round 17): a donated direct kernel is a pump-file
region (detected structurally via its donate_argnums decorator), so a
host sync traced into it is a per-compile constant — the
silent-wrong-answer class — and its donation set must stay exactly the
strip_mutable pair. Scanned under the SPOOFED rel path
``cruise_control_tpu/analyzer/direct.py`` by tests/test_ccsa.py; under
its own path the file is silent for CCSA001 (path-scoped rule)."""

from functools import partial

import jax


@partial(jax.jit, donate_argnums=(0, 1))
def direct_transport_rounds_donated(assignment, leader_slot, rest, plan):
    sweeps = float(plan)            # finding: CCSA001 host sync in region
    moves = plan.tolist()           # finding: CCSA001 host sync in region
    # ccsa: ok[CCSA001] fixture: annotated deliberate readback
    budget = int(plan)
    return assignment, leader_slot, sweeps, moves, budget


@partial(jax.jit, donate_argnums=(0, 1, 2))
def direct_donates_topology(assignment, leader_slot, rest):
    # finding: CCSA002 — `rest` is refresh-cache-shared topology
    return assignment, leader_slot, rest


def run_direct_pass(state, plan):
    # NOT a region (plain host driver): a synchronous readback after a
    # single dispatch is the documented contract — silent here.
    return int(plan)


# --- round 21: mesh traced-driver donation form (CCSA002) ----------------
# The sharded direct pre-pass donates THROUGH shard_map: the argnums
# must resolve to the body's same-position parameters, exactly like the
# megabatch's vmap form.
from jax.experimental.shard_map import shard_map  # noqa: E402

MESH = None
SPECS = None


def mesh_direct_body_donated(assignment, leader_slot, rest, masks):
    return assignment, leader_slot


mesh_direct_bad = jax.jit(
    shard_map(mesh_direct_body_donated, mesh=MESH, in_specs=SPECS,
              out_specs=SPECS),
    donate_argnums=(0, 1, 2))   # finding: CCSA002 — `rest` is topology

mesh_direct_ok = jax.jit(
    shard_map(mesh_direct_body_donated, mesh=MESH, in_specs=SPECS,
              out_specs=SPECS),
    donate_argnums=(0, 1))      # clean: exactly the strip_mutable pair


# --- round 21: sparse-plan rounding PRNG (CCSA004) -----------------------
# Under the spoofed analyzer/direct.py path the module carries the
# byte-identical replan contract: rounding uniforms come from the
# crc32-seeded splitmix hash ONLY.
import random  # noqa: E402
import zlib  # noqa: E402


def rounding_seed_bad():
    return random.random()          # finding: CCSA004 global-random draw


def rounding_seed_good(salt: str) -> int:
    return zlib.crc32(salt.encode("utf-8"))   # clean: crc32 derivation


def rounding_jitter_tolerated():
    # ccsa: ok[CCSA004] fixture: documented non-replayed diagnostic
    return random.uniform(0.0, 1.0)
