"""CCSA004 fixture: wall-clock and global-``random`` leaks in a futures
sampler (tests lint this file under a spoofed
cruise_control_tpu/futures/generator.py path — the round-15 modules sit
under the same byte-identical determinism contract as the twin)."""

import random
import time
import zlib


def bad_sample_tick() -> int:
    return int(time.time()) % 60          # finding: wall clock in sampler


def bad_sample_factor() -> float:
    return 1.0 + random.random()          # finding: global random state


def good_sample_factor(seed: int) -> float:
    return 1.0 + zlib.crc32(f"{seed}:factor".encode()) / 0xFFFFFFFF


def injected(clock=time.monotonic) -> float:
    return clock()                        # clean: reference is the seam


def timed_probe() -> float:
    # ccsa: ok[CCSA004] fixture: observability-only timer, never enters
    # the sampled event stream or the ranked score JSON
    return time.perf_counter()
