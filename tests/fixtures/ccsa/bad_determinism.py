"""CCSA004 fixture: PYTHONHASHSEED-dependent hash() (repo-wide check)
and wall-clock calls (deterministic-module check — tests lint this file
under a spoofed testing/simulator.py path)."""

import time


def unstable_key(topic: str) -> int:
    return hash(topic) % 1000        # finding anywhere in the repo


def stamp() -> float:
    return time.time()               # finding under a deterministic path


def injected(clock=time.monotonic) -> float:
    return clock()                   # clean: reference is the seam


def tolerated(parts: tuple) -> int:
    # ccsa: ok[CCSA004] fixture: in-process memo key, never persisted
    return hash(parts)


class Keyed:
    def __init__(self, value):
        self.value = value

    def __hash__(self) -> int:
        return hash(self.value)      # clean: __hash__ is exempt
