"""CCSA004 fixture: a forecaster-shaped module that stamps projections
with the wall clock and samples noise from the global ``random`` state
(tests lint this file under the spoofed
cruise_control_tpu/forecast/forecaster.py path — the round-19 projection
feeds SOLVER INPUTS and anomaly decisions, so the fit must be a pure
function of the history tensor; the detector's deadlines ride the
injected clock seam)."""

import random
import time


def bad_projection_stamp() -> float:
    return time.time()                   # finding: wall clock inline


def bad_band_noise() -> float:
    return random.random()               # finding: global random state


def injected_deadline(clock=time.time) -> float:
    return clock()                       # clean: reference is the seam


def timed_fit() -> float:
    # ccsa: ok[CCSA004] fixture: observability-only fit duration, never
    # enters the projection or the anomaly decision
    return time.perf_counter()
