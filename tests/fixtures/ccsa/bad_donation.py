"""CCSA002 fixture: donation outside the mutable set."""

from functools import partial

import jax


@partial(jax.jit, donate_argnums=(0, 1, 2))
def donates_topology(assignment, leader_slot, rest):   # finding: rest
    return assignment, leader_slot, rest


@partial(jax.jit, donate_argnums=(0, 1))
def donates_mutable_set(assignment, leader_slot):      # clean
    return assignment, leader_slot


# ccsa: ok[CCSA002] fixture: scratch buffer owned by the caller-free test
@partial(jax.jit, donate_argnums=(0,))
def suppressed_donation(scratch):
    return scratch * 2


# Megabatch call form (round 14): the donation set resolves THROUGH the
# vmap wrapper to the batched body's same-position parameters.
def batched_body(assignment, leader_slot, rest):
    return assignment, leader_slot, rest


megabatch_bad = jax.jit(jax.vmap(batched_body),
                        donate_argnums=(0, 1, 2))  # finding: rest

megabatch_ok = jax.jit(jax.vmap(batched_body),
                       donate_argnums=(0, 1))      # clean
