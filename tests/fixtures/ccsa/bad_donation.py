"""CCSA002 fixture: donation outside the mutable set."""

from functools import partial

import jax


@partial(jax.jit, donate_argnums=(0, 1, 2))
def donates_topology(assignment, leader_slot, rest):   # finding: rest
    return assignment, leader_slot, rest


@partial(jax.jit, donate_argnums=(0, 1))
def donates_mutable_set(assignment, leader_slot):      # clean
    return assignment, leader_slot


# ccsa: ok[CCSA002] fixture: scratch buffer owned by the caller-free test
@partial(jax.jit, donate_argnums=(0,))
def suppressed_donation(scratch):
    return scratch * 2
