"""CCSA001 fixture: host syncs inside the MEGABATCH pump region.

Linted by tests/test_ccsa.py under a spoofed
``cruise_control_tpu/fleet/megabatch.py`` relative path (the rule's pump
modules grew the fleet megabatch in round 14); the batched enqueue
closure shares the ``enqueue`` region name, so it is scoped too."""

import numpy as np


def run_megabatch_pass(enqueue, st, active, pass_cap):
    def make_enqueue():
        def enqueue_inner(st, active, budget):
            return st, active, budget
        return enqueue_inner

    st, active, applied, rounds, donated, ring = enqueue(st, active,
                                                         pass_cap)
    per_cluster = np.asarray(rounds)            # finding: device transfer
    occupancy = int(active.sum())               # finding: blocks the pump
    # ccsa: ok[CCSA001] fixture: documented intentional readback
    moved = np.asarray(applied)
    return st, per_cluster, occupancy, moved, donated, ring


def enqueue(st, active, budget):
    batched = float(budget)                     # finding: enqueue region
    return st, active, batched
