"""CCSA007 fixture: module-level mutable containers mutated at runtime."""

import threading

_CACHE: dict = {}
_LOCK = threading.Lock()
_TOLERATED: list = []
_TABLE: list = []
for _i in range(4):
    _TABLE.append(_i * _i)           # clean: import-time initialization


def put(key, value):
    _CACHE[key] = value              # finding: unlocked mutation


def drop(key):
    _CACHE.pop(key, None)            # finding: unlocked mutation


def put_locked(key, value):
    with _LOCK:
        _CACHE[key] = value          # clean: lock-guarded


def shadowed(values):
    _CACHE = {}                      # local shadow, not the module global
    _CACHE["n"] = len(values)        # clean
    return _CACHE


def mark(x):
    # ccsa: ok[CCSA007] fixture: single-threaded accumulator by contract
    _TOLERATED.append(x)
