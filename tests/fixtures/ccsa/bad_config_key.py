"""CCSA005 fixture: dotted-key literals that no ConfigDef declares."""


def read(cfg):
    a = cfg.get("totally.unknown.key")          # finding
    b = cfg.get_int("another.unknown.key")      # finding
    c = cfg.get("anomaly.detection.interval.ms")   # clean: declared
    # ccsa: ok[CCSA005] fixture: external key space
    d = cfg.get("externally.owned.key")
    e = cfg.get("plainword")                    # clean: not dotted
    return a, b, c, d, e
