"""CCSA003 fixture: Python side effects inside lax body functions."""

import jax


def leaky_loop(x):
    log = []

    def loop_cond(carry):
        return carry < 3

    def loop_body(carry):
        log.append(carry)            # finding: runs once, at trace time
        return carry + 1

    return jax.lax.while_loop(loop_cond, loop_body, x), log


def leaky_scan(xs):
    totals = {}

    def scan_step(carry, x):
        totals["n"] = carry          # finding: subscript write upward
        return carry + x, x

    return jax.lax.scan(scan_step, 0, xs), totals


def tolerated_loop(x):
    trace_marks = []

    def ok_cond(carry):
        return carry < 3

    def ok_body(carry):
        # ccsa: ok[CCSA003] fixture: deliberate trace-time-only marker
        trace_marks.append(1)
        return carry + 1

    return jax.lax.while_loop(ok_cond, ok_body, x), trace_marks
