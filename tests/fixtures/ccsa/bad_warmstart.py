"""CCSA004 + CCSA007 fixture: a warmstart-shaped module with an
age-stamped seed (wall-clock leak into solver-input state) and an
unlocked module-level prewarm-manager registry (tests lint this file
under the spoofed cruise_control_tpu/warmstart.py path — the round-18
warm path feeds SOLVER INPUTS and sits under the deterministic-module
contract; the prewarm registry is module-level shared state and must
mutate under its lock)."""

import threading
import time

_MANAGERS: dict = {}
_REGISTRY_LOCK = threading.Lock()


def bad_seed_stamp() -> float:
    return time.monotonic()              # finding: wall clock inline


def injected_stamp(monotonic=time.monotonic) -> float:
    return monotonic()                   # clean: reference is the seam


def bad_register(opt, mgr) -> None:
    _MANAGERS[id(opt)] = mgr             # finding: unlocked registry write


def good_register(opt, mgr) -> None:
    with _REGISTRY_LOCK:
        _MANAGERS[id(opt)] = mgr         # clean: lock-guarded


def tolerated_register(opt, mgr) -> None:
    # ccsa: ok[CCSA007] fixture: import-time-only single writer by
    # documented contract
    _MANAGERS[id(opt)] = mgr


def timed_sweep() -> float:
    # ccsa: ok[CCSA004] fixture: observability-only duration, never
    # enters seed validity or solver inputs
    return time.perf_counter()
