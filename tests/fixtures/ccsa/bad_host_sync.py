"""CCSA001 fixture: host syncs inside the async pump region.

Linted by tests/test_ccsa.py under a spoofed ``analyzer/chain.py``
relative path (the rule is scoped to the pump modules)."""

import numpy as np


def run_bounded_pass(enqueue, st, pass_cap):
    st, applied, rounds, donated, ring = enqueue(st, pass_cap)
    moves = float(applied)                      # finding: blocks the pump
    snapshot = np.asarray(ring)                 # finding: device transfer
    # ccsa: ok[CCSA001] fixture: documented intentional readback
    rounds_read = int(rounds)
    return st, moves, rounds_read, donated, snapshot
