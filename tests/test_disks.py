"""JBOD disk model + intra-broker balancing (reference parity: Disk.java,
IntraBrokerDiskCapacityGoal, IntraBrokerDiskUsageDistributionGoal,
RemoveDisksRunnable)."""

import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

from cruise_control_tpu.analyzer.goals.intra_broker import (
    IntraBrokerDiskCapacityGoal, IntraBrokerDiskUsageDistributionGoal,
)
from cruise_control_tpu.common.resources import Resource
from cruise_control_tpu.model.builder import ClusterModelBuilder
from cruise_control_tpu.model.disks import (
    DiskMeta, DiskTensors, balance_intra_broker, build_disk_tensors,
    diff_intra_broker_moves, disk_load, intra_broker_violations,
)

CAP = {Resource.CPU: 100.0, Resource.NW_IN: 1e6, Resource.NW_OUT: 1e6,
       Resource.DISK: 1e6}


def _cluster(num_brokers=2, parts=6, disk_mb=100.0):
    b = ClusterModelBuilder()
    for i in range(num_brokers):
        b.add_broker(i, f"r{i}", CAP)
    load = {Resource.CPU: 1.0, Resource.NW_IN: 10.0, Resource.NW_OUT: 10.0,
            Resource.DISK: disk_mb}
    for p in range(parts):
        b.add_partition("t0", p, [p % num_brokers], leader_load=load)
    return b.build()


def _disks(state, meta, num_dirs=2, capacity=400.0, skew_all_to_first=True):
    p, s = state.assignment.shape
    b = state.num_brokers
    assign = np.asarray(state.assignment)
    disk_assign = np.where(assign >= 0,
                           0 if skew_all_to_first else assign % num_dirs, -1)
    cap = np.full((b, num_dirs), capacity, dtype=np.float32)
    alive = np.ones((b, num_dirs), dtype=bool)
    disks = DiskTensors(disk_assignment=jnp.asarray(disk_assign, jnp.int32),
                        disk_capacity=jnp.asarray(cap),
                        disk_alive=jnp.asarray(alive))
    dm = DiskMeta(dir_names=[[f"/d{k}" for k in range(num_dirs)]
                             for _ in range(b)])
    return disks, dm


def test_disk_load_accounting():
    state, meta = _cluster(num_brokers=2, parts=6, disk_mb=100.0)
    disks, _ = _disks(state, meta)
    loads = np.asarray(disk_load(state, disks))
    # 3 partitions per broker, all on disk 0.
    np.testing.assert_allclose(loads[:, 0], 300.0)
    np.testing.assert_allclose(loads[:, 1], 0.0)


def test_capacity_goal_drains_overfull_disk():
    state, meta = _cluster(num_brokers=2, parts=6, disk_mb=100.0)
    disks, dm = _disks(state, meta, capacity=300.0)   # 300 on d0, cap·0.8=240
    goal = IntraBrokerDiskCapacityGoal()
    assert float(goal.violations(state, disks).sum()) > 0
    fixed = goal.optimize(state, disks)
    assert float(goal.violations(state, fixed).sum()) == pytest.approx(0.0)
    moves = diff_intra_broker_moves(disks, fixed, state, meta, dm)
    assert moves and all(m.source_logdir == "/d0" and
                         m.destination_logdir == "/d1" for m in moves)


def test_dead_disk_fully_drains():
    state, meta = _cluster(num_brokers=2, parts=6, disk_mb=100.0)
    disks, dm = _disks(state, meta, capacity=1000.0)
    dead = np.asarray(disks.disk_alive).copy()
    dead[0, 0] = False                      # broker 0's /d0 dies
    disks = dataclasses.replace(disks, disk_alive=jnp.asarray(dead))
    fixed = balance_intra_broker(state, disks, capacity_threshold=0.8)
    loads = np.asarray(disk_load(state, fixed))
    assert loads[0, 0] == pytest.approx(0.0), "dead disk must drain"
    assert loads[0, 1] == pytest.approx(300.0)
    # Broker 1 untouched.
    assert loads[1, 0] == pytest.approx(300.0)


def test_usage_distribution_goal_balances_within_broker():
    state, meta = _cluster(num_brokers=1, parts=8, disk_mb=100.0)
    disks, _dm = _disks(state, meta, num_dirs=2, capacity=2000.0)
    goal = IntraBrokerDiskUsageDistributionGoal()
    fixed = goal.optimize(state, disks)
    loads = np.asarray(disk_load(state, fixed))[0]
    assert abs(loads[0] - loads[1]) <= 100.0, loads   # within one replica


def test_build_disk_tensors_from_backend_facts():
    state, meta = _cluster(num_brokers=2, parts=4, disk_mb=50.0)
    logdirs = {0: {"/a": True, "/b": True}, 1: {"/a": True, "/b": False}}
    replica_dirs = {("t0", 0, 0): "/a", ("t0", 2, 0): "/b",
                    ("t0", 1, 1): "/a", ("t0", 3, 1): "/a"}
    disks, dm = build_disk_tensors(state, meta, logdirs, replica_dirs,
                                   capacity_by_dir={(0, "/a"): 111.0})
    assert dm.dir_names[0] == ["/a", "/b"]
    cap = np.asarray(disks.disk_capacity)
    assert cap[0, 0] == pytest.approx(111.0)
    alive = np.asarray(disks.disk_alive)
    assert alive[1, 0] and not alive[1, 1]
    loads = np.asarray(disk_load(state, disks))
    assert loads[0, 0] == pytest.approx(50.0)
    assert loads[0, 1] == pytest.approx(50.0)
    assert loads[1, 0] == pytest.approx(100.0)


def test_facade_remove_disks_and_rebalance_disk():
    from cruise_control_tpu.config.cruise_control_config import CruiseControlConfig
    from cruise_control_tpu.executor.admin import InMemoryAdminBackend, PartitionState
    from cruise_control_tpu.executor.executor import Executor
    from cruise_control_tpu.facade import CruiseControl
    from cruise_control_tpu.monitor import LoadMonitor, StaticCapacityResolver
    from cruise_control_tpu.monitor.sampling import SyntheticSampler

    parts = {("t0", p): PartitionState("t0", p, (p % 2,), p % 2,
                                       isr=(p % 2,)) for p in range(6)}
    backend = InMemoryAdminBackend(parts.values())
    backend.enable_jbod({0: ["/d0", "/d1"], 1: ["/d0", "/d1"]})
    cfg = CruiseControlConfig({"partition.metrics.window.ms": 1000,
                               "num.partition.metrics.windows": 3,
                               "min.valid.partition.ratio": 0.0,
                               "failed.brokers.file.path": ""})
    caps = StaticCapacityResolver({}, {Resource.CPU: 100.0, Resource.DISK: 1e7,
                                       Resource.NW_IN: 1e6, Resource.NW_OUT: 1e6})
    monitor = LoadMonitor(cfg, backend, samplers=[SyntheticSampler()],
                          capacity_resolver=caps)
    cc = CruiseControl(cfg, backend, load_monitor=monitor,
                       executor=Executor(backend, synchronous=True))
    for k in range(1, 4):
        monitor.task_runner.run_sampling_once(end_ms=k * 1000)

    res = cc.remove_disks({0: ["/d0"]}, dryrun=False)
    assert res.executed
    after = backend.replica_logdirs()
    for (topic, part, broker), d in after.items():
        if broker == 0:
            assert d == "/d1", (topic, part, d)
    with pytest.raises(ValueError, match="no remaining alive"):
        cc.remove_disks({0: ["/d0", "/d1"]})

    res2 = cc.rebalance_disk(dryrun=True)
    assert res2.operation == "rebalance_disk"
    assert not res2.executed


def test_excluded_topics_regex_pins_replicas_on_disk_ops():
    """topics.excluded.from.partition.movement binds intra-broker moves
    too: an excluded topic's replicas keep their log dirs through
    rebalance_disk (the reference's intra-broker goals respect
    optimizationOptions.excludedTopics)."""
    from cruise_control_tpu.config.cruise_control_config import CruiseControlConfig
    from cruise_control_tpu.executor.admin import InMemoryAdminBackend, PartitionState
    from cruise_control_tpu.executor.executor import Executor
    from cruise_control_tpu.facade import CruiseControl
    from cruise_control_tpu.monitor import LoadMonitor, StaticCapacityResolver
    from cruise_control_tpu.monitor.sampling import SyntheticSampler

    # All replicas on broker 0's /d0 — heavy imbalance that rebalance_disk
    # would normally spread to /d1.
    parts = {}
    for p in range(4):
        parts[("pinned", p)] = PartitionState("pinned", p, (0,), 0, isr=(0,))
        parts[("free", p)] = PartitionState("free", p, (0,), 0, isr=(0,))
    backend = InMemoryAdminBackend(parts.values())
    backend.enable_jbod({0: ["/d0", "/d1"]})
    cfg = CruiseControlConfig({
        "partition.metrics.window.ms": 1000,
        "num.partition.metrics.windows": 3,
        "min.valid.partition.ratio": 0.0,
        "failed.brokers.file.path": "",
        "topics.excluded.from.partition.movement": "pinned"})
    caps = StaticCapacityResolver({}, {Resource.CPU: 100.0, Resource.DISK: 1e7,
                                       Resource.NW_IN: 1e6, Resource.NW_OUT: 1e6})
    monitor = LoadMonitor(cfg, backend, samplers=[SyntheticSampler()],
                          capacity_resolver=caps)
    cc = CruiseControl(cfg, backend, load_monitor=monitor,
                       executor=Executor(backend, synchronous=True))
    for k in range(1, 4):
        monitor.task_runner.run_sampling_once(end_ms=k * 1000)

    before = dict(backend.replica_logdirs())
    cc.rebalance_disk(dryrun=False)
    after = backend.replica_logdirs()
    for key, d in after.items():
        if key[0] == "pinned":
            assert d == before[key], f"pinned replica moved: {key}"


def test_movable_mask_pins_replicas_in_balancer_kernel():
    """balance_intra_broker(movable=...) never moves pinned replicas, and
    still balances via the movable ones (deterministic kernel-level check
    — the facade path above depends on sampled loads)."""
    import jax.numpy as jnp

    from cruise_control_tpu.model.disks import (
        balance_intra_broker, build_disk_tensors, disk_load,
    )
    from cruise_control_tpu.model.fixtures import small_unbalanced

    state, meta = small_unbalanced(num_brokers=1, partitions_per_topic=4,
                                   rf=1)
    logdirs = {0: {"/a": True, "/b": True}}
    # every replica starts on /a — heavy imbalance
    replica_dirs = {(t, p, 0): "/a" for (t, p) in meta.partition_index}
    disks, dm = build_disk_tensors(state, meta, logdirs, replica_dirs,
                                   default_capacity=1e6)
    # pin topic t1 (its partitions must stay on /a)
    pinned = jnp.asarray(np.array(
        [t == "t1" for t, _p in meta.partition_index]
        + [False] * (state.num_partitions - len(meta.partition_index))))
    balanced = balance_intra_broker(state, disks, balance_band=(0.8, 1.2),
                                    movable=~pinned)
    assign = np.asarray(balanced.disk_assignment)
    orig = np.asarray(disks.disk_assignment)
    for i, (t, _p) in enumerate(meta.partition_index):
        if t == "t1":
            assert assign[i, 0] == orig[i, 0], f"pinned {t}-{_p} moved"
    # the movable topic's replicas actually spread across disks
    loads = np.asarray(disk_load(state, balanced))
    assert loads[0, 1] > 0.0, "no movable replica reached /b"
