"""Equivalence oracle for the two attach_cumulative implementations.

The O(m²) pairwise-matmul form is the reference semantics
(candidates.attach_cumulative's original body); the O(m log m)
sorted-segment form must produce the same pre_* fields and has_earlier
mask up to f32 reassociation on random rank-ordered batches.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

from cruise_control_tpu.analyzer.candidates import (
    CandidateDeltas, attach_cumulative_segments,
)

_PRE_FIELDS = [
    "pre_src_load", "pre_dst_load", "pre_src_count", "pre_dst_count",
    "pre_src_leaders", "pre_dst_leaders", "pre_src_topic_count",
    "pre_dst_topic_count", "pre_src_topic_leaders", "pre_dst_pot",
    "pre_dst_lbi",
]


def _matmul_reference(sub, considered, pot_delta, lbi_delta):
    """The original [m, m] mask-matmul attach_cumulative, inlined as the
    oracle so the production dispatcher can default to segments."""
    m = sub.partition.shape[0]
    idx = jnp.arange(m)
    earlier = (idx[:, None] > idx[None, :]) & considered[None, :]
    same_dst = earlier & (sub.dst_broker[:, None] == sub.dst_broker[None, :])
    same_src = earlier & (sub.src_broker[:, None] == sub.src_broker[None, :])
    cross_sd = earlier & (sub.src_broker[:, None] == sub.dst_broker[None, :])
    cross_ds = earlier & (sub.dst_broker[:, None] == sub.src_broker[None, :])
    same_topic = sub.topic[:, None] == sub.topic[None, :]
    f32 = jnp.float32
    rep = sub.replica_delta.astype(f32)
    lead = sub.leader_delta.astype(f32)
    r = sub.load_delta.shape[1]
    src_vals = jnp.concatenate(
        [sub.load_delta, rep[:, None], lead[:, None]], axis=1)
    dst_vals = jnp.concatenate(
        [sub.load_delta, rep[:, None], lead[:, None], pot_delta[:, None],
         lbi_delta[:, None]], axis=1)
    src_out = same_src.astype(f32) @ src_vals
    dst_out = same_dst.astype(f32) @ dst_vals
    st_out = (same_src & same_topic).astype(f32) @ jnp.stack(
        [rep, lead], axis=1)
    dt_count = ((same_dst & same_topic).astype(f32) @ rep[:, None])[:, 0]
    has_earlier = (same_dst | same_src | cross_sd | cross_ds).any(axis=1)
    return dataclasses.replace(
        sub, pre_src_load=src_out[:, :r], pre_dst_load=dst_out[:, :r],
        pre_src_count=src_out[:, r], pre_dst_count=dst_out[:, r],
        pre_src_leaders=src_out[:, r + 1], pre_dst_leaders=dst_out[:, r + 1],
        pre_src_topic_count=st_out[:, 0], pre_dst_topic_count=dt_count,
        pre_src_topic_leaders=st_out[:, 1], pre_dst_pot=dst_out[:, r + 2],
        pre_dst_lbi=dst_out[:, r + 3]), has_earlier


def _random_batch(rng, m, b, t):
    kind_move = rng.random(m) < 0.8
    return CandidateDeltas(
        src_broker=jnp.asarray(rng.integers(0, b, m), jnp.int32),
        dst_broker=jnp.asarray(rng.integers(0, b, m), jnp.int32),
        load_delta=jnp.asarray(rng.random((m, 4)), jnp.float32),
        replica_delta=jnp.asarray(kind_move, jnp.int32),
        leader_delta=jnp.asarray(rng.random(m) < 0.5, jnp.int32),
        partition=jnp.asarray(rng.integers(0, 10 * m, m), jnp.int32),
        topic=jnp.asarray(rng.integers(0, t, m), jnp.int32),
        src_slot=jnp.zeros(m, jnp.int32),
        dst_slot=jnp.zeros(m, jnp.int32),
        valid=jnp.asarray(rng.random(m) < 0.9),
    )


@pytest.mark.parametrize("m,b,t,seed", [
    (64, 5, 3, 0),       # dense broker collisions
    (256, 40, 11, 1),
    (512, 1000, 700, 2),  # sparse: most groups singleton
    (333, 7, 2, 3),       # odd size, heavy topic collisions
])
def test_segment_matches_matmul(m, b, t, seed):
    rng = np.random.default_rng(seed)
    sub = _random_batch(rng, m, b, t)
    considered = jnp.asarray(rng.random(m) < 0.7)
    pot = jnp.asarray(rng.random(m), jnp.float32)
    lbi = jnp.asarray(rng.random(m), jnp.float32)

    ref, he_ref = _matmul_reference(sub, considered, pot, lbi)
    seg, he_seg = attach_cumulative_segments(sub, considered, pot, lbi)

    np.testing.assert_array_equal(np.asarray(he_ref), np.asarray(he_seg))
    for f in _PRE_FIELDS:
        np.testing.assert_allclose(
            np.asarray(getattr(ref, f)), np.asarray(getattr(seg, f)),
            rtol=1e-5, atol=1e-4, err_msg=f)
