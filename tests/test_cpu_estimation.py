"""CPU estimation semantics (ModelUtils / LinearRegressionModelParameters parity)."""

import numpy as np
import pytest

from cruise_control_tpu.model.cpu_estimation import (
    CpuEstimator, CpuModelCoefficients, LinearRegressionCpuModel,
    estimate_leader_cpu_util, follower_cpu_util_from_leader_load,
)


def test_static_estimate_splits_broker_cpu_by_traffic_share():
    # One partition carrying all of the broker's leader traffic gets the
    # whole leader share of broker CPU.
    est = estimate_leader_cpu_util(
        broker_cpu_util=np.array([0.5]),
        broker_leader_bytes_in=np.array([100.0]),
        broker_leader_bytes_out=np.array([200.0]),
        broker_follower_bytes_in=np.array([0.0]),
        partition_bytes_in=np.array([100.0]),
        partition_bytes_out=np.array([200.0]))
    assert est == pytest.approx(0.5)

    # Half the traffic → half the leader-attributed CPU.
    est_half = estimate_leader_cpu_util(
        np.array([0.5]), np.array([100.0]), np.array([200.0]), np.array([0.0]),
        np.array([50.0]), np.array([100.0]))
    assert est_half == pytest.approx(0.25)


def test_static_estimate_zero_broker_traffic_is_zero():
    est = estimate_leader_cpu_util(
        np.array([0.9]), np.array([0.0]), np.array([0.0]), np.array([5.0]),
        np.array([0.0]), np.array([0.0]))
    assert est == 0.0


def test_static_estimate_inconsistent_rates_returns_nan():
    # Partition rate > broker rate beyond the 5% error factor with a stable
    # broker rate ⇒ the reference returns null; we return NaN.
    est = estimate_leader_cpu_util(
        np.array([0.5]), np.array([100.0]), np.array([100.0]), np.array([0.0]),
        np.array([200.0]), np.array([10.0]))
    assert np.isnan(est[0])


def test_follower_cpu_from_leader_load():
    coef = CpuModelCoefficients()
    out = follower_cpu_util_from_leader_load(
        np.array([100.0]), np.array([100.0]), np.array([0.4]), coef)
    expect = 0.4 * (coef.follower_bytes_in * 100.0) / (
        coef.leader_bytes_in * 100.0 + coef.leader_bytes_out * 100.0)
    assert out == pytest.approx(expect)
    assert follower_cpu_util_from_leader_load(
        np.array([0.0]), np.array([0.0]), np.array([0.4]), coef) == 0.0


def test_linear_regression_recovers_coefficients():
    rng = np.random.default_rng(0)
    n = 4000
    lin = rng.uniform(0, 1000, n)
    lout = rng.uniform(0, 1000, n)
    fin = rng.uniform(0, 1000, n)
    true = np.array([3e-4, 1e-4, 5e-5])
    cpu = np.clip(true[0] * lin + true[1] * lout + true[2] * fin, 0, 1)
    model = LinearRegressionCpuModel(num_buckets=10, max_per_bucket=1000,
                                     min_completeness=0.3)
    model.add_observations(cpu, lin, lout, fin)
    assert model.train()
    np.testing.assert_allclose(model.coefficients, true, rtol=1e-3)
    est = model.estimate_leader_cpu_util(np.array([100.0]), np.array([100.0]))
    assert est == pytest.approx(true[0] * 100 + true[1] * 100, rel=1e-3)


def test_linear_regression_requires_bucket_diversity():
    model = LinearRegressionCpuModel(num_buckets=10, min_completeness=0.5)
    # All observations in one CPU bucket → not complete, no train.
    model.add_observations(np.full(100, 0.05), np.ones(100), np.ones(100),
                           np.ones(100))
    assert not model.train()
    assert model.training_completeness == pytest.approx(0.1)


def test_estimator_facade_switches_models():
    est = CpuEstimator()
    static = est.leader_cpu(np.array([0.5]), np.array([100.0]),
                            np.array([200.0]), np.array([0.0]),
                            np.array([100.0]), np.array([200.0]))
    assert static == pytest.approx(0.5)

    model = LinearRegressionCpuModel(num_buckets=5, min_completeness=0.2)
    rng = np.random.default_rng(1)
    lin = rng.uniform(0, 100, 500)
    model.add_observations(np.clip(2e-3 * lin, 0, 1), lin, np.zeros(500),
                           np.zeros(500))
    assert model.train()
    est2 = CpuEstimator(linear_model=model, use_linear_regression=True)
    out = est2.leader_cpu(None, None, None, None, np.array([50.0]),
                          np.array([0.0]))
    assert out == pytest.approx(0.1, rel=1e-2)
