"""Serving front door (round 20): the async task engine's per-class
queues and lifecycle, the model-generation response cache, cross-request
coalescing, admission shedding, and the deterministic load-test harness
— units plus end-to-end byte-identity through the REAL api."""

import json
import threading
import time

import pytest

from cruise_control_tpu.api.server import CruiseControlApi
from cruise_control_tpu.api.user_tasks import (
    USER_TASK_HEADER, TaskOwnershipError, UserTaskManager,
)
from cruise_control_tpu.common.resources import Resource
from cruise_control_tpu.config.cruise_control_config import CruiseControlConfig
from cruise_control_tpu.executor.admin import InMemoryAdminBackend, PartitionState
from cruise_control_tpu.executor.executor import Executor
from cruise_control_tpu.facade import CruiseControl
from cruise_control_tpu.fleet import FleetRegistry, FleetScheduler
from cruise_control_tpu.monitor import LoadMonitor, StaticCapacityResolver
from cruise_control_tpu.monitor.sampling import SyntheticSampler
from cruise_control_tpu.serving import (
    AdmissionController, AdmissionShedError, AsyncTaskEngine, ResponseCache,
    TaskClass, TaskQueueFullError, canonical_params, task_class_of,
)
from cruise_control_tpu.serving import loadgen
from cruise_control_tpu.serving.cache import CACHEABLE_ENDPOINTS

_WAIT_S = 20.0


def _poll(predicate, timeout_s=_WAIT_S):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(0.005)
    return predicate()


# ---- task engine ---------------------------------------------------------

def test_task_class_mapping():
    assert task_class_of("PROPOSALS") is TaskClass.SOLVER
    assert task_class_of("COMPARE_FUTURES") is TaskClass.SOLVER
    assert task_class_of("REBALANCE") is TaskClass.SOLVER
    assert task_class_of("LOAD") is TaskClass.VIEWER
    assert task_class_of("PARTITION_LOAD") is TaskClass.VIEWER


def test_engine_lifecycle_and_results():
    engine = AsyncTaskEngine(viewer_threads=1, solver_threads=1)
    try:
        ev = threading.Event()
        fut, rec = engine.submit("LOAD", lambda: ev.wait(_WAIT_S) and "ok",
                                 task_id="t-run")
        assert _poll(lambda: rec.lifecycle == "running")
        ev.set()
        assert fut.result(timeout=_WAIT_S) == "ok"
        assert rec.lifecycle == "done"
        assert engine.lifecycle("t-run") == "done"

        def boom():
            raise RuntimeError("kaput")

        fut2, rec2 = engine.submit("PROPOSALS", boom, task_id="t-fail")
        with pytest.raises(RuntimeError, match="kaput"):
            fut2.result(timeout=_WAIT_S)
        assert rec2.lifecycle == "failed"
        assert rec2.klass is TaskClass.SOLVER
        assert engine.completed[TaskClass.SOLVER] == 1
    finally:
        engine.shutdown()


def test_engine_queue_capacity_sheds_with_retry_after():
    engine = AsyncTaskEngine(viewer_capacity=2, viewer_threads=1,
                             solver_threads=1)
    try:
        ev = threading.Event()
        _fut, rec = engine.submit("LOAD", lambda: ev.wait(_WAIT_S),
                                  task_id="blocker")
        assert _poll(lambda: rec.lifecycle == "running")
        engine.submit("LOAD", lambda: 1, task_id="q1")
        engine.submit("LOAD", lambda: 2, task_id="q2")
        assert engine.queue_depth(TaskClass.VIEWER) == 2
        with pytest.raises(TaskQueueFullError) as exc:
            engine.submit("LOAD", lambda: 3, task_id="q3")
        assert exc.value.klass is TaskClass.VIEWER
        assert exc.value.capacity == 2
        assert exc.value.retry_after_s >= 1.0
        ev.set()
    finally:
        engine.shutdown()


def test_engine_shutdown_evicts_queued_and_runs_inline_after():
    engine = AsyncTaskEngine(viewer_threads=1, solver_threads=1)
    ev = threading.Event()
    _fut, rec = engine.submit("LOAD", lambda: ev.wait(_WAIT_S),
                              task_id="hold")
    assert _poll(lambda: rec.lifecycle == "running")
    fut2, rec2 = engine.submit("LOAD", lambda: "never", task_id="queued")
    closer = threading.Thread(target=engine.shutdown, daemon=True)
    closer.start()
    assert _poll(lambda: fut2.cancelled())
    assert rec2.lifecycle == "evicted"
    ev.set()
    closer.join(timeout=_WAIT_S)
    assert not closer.is_alive()
    # The FleetScheduler discipline: submit after shutdown runs INLINE.
    fut3, rec3 = engine.submit("PROPOSALS", lambda: 42, task_id="late")
    assert fut3.result(timeout=0) == 42
    assert rec3.lifecycle == "done"


def test_engine_ewma_service_time_and_retry_after():
    clock = [0.0]

    def monotonic():
        return clock[0]

    engine = AsyncTaskEngine(viewer_threads=1, solver_threads=1,
                             monotonic=monotonic)
    try:
        def takes(seconds):
            def fn():
                clock[0] += seconds
            return fn

        engine.submit("LOAD", takes(2.0), task_id="a")[0].result(_WAIT_S)
        assert engine.service_time_s(TaskClass.VIEWER) == pytest.approx(2.0)
        engine.submit("LOAD", takes(4.0), task_id="b")[0].result(_WAIT_S)
        # EWMA(0.2): 0.8 * 2.0 + 0.2 * 4.0
        assert engine.service_time_s(TaskClass.VIEWER) == pytest.approx(2.4)
        # depth * est / workers, floored at 1s.
        assert engine.retry_after_s(TaskClass.VIEWER, 2) \
            == pytest.approx(4.8)
        assert engine.retry_after_s(TaskClass.VIEWER, 0) == 1.0
        # SOLVER never observed: seeded default, not the viewer EWMA.
        assert engine.service_time_s(TaskClass.SOLVER) == pytest.approx(2.0)
    finally:
        engine.shutdown()


def test_engine_evict_marks_done_records_only():
    engine = AsyncTaskEngine(viewer_threads=1, solver_threads=1)
    try:
        fut, rec = engine.submit("LOAD", lambda: 1, task_id="gone")
        fut.result(timeout=_WAIT_S)
        engine.evict("gone")
        assert rec.lifecycle == "evicted"
        assert engine.evicted == 1
        engine.evict("gone")           # idempotent
        engine.evict("never-existed")  # unknown ids are a no-op
        assert engine.evicted == 1
        assert engine.stats()["evicted"] == 1
    finally:
        engine.shutdown()


# ---- response cache + canonical params -----------------------------------

def test_canonical_params_order_independent_and_busting():
    a = canonical_params("PROPOSALS", {"goals": ("G1",), "verbose": True})
    b = canonical_params("PROPOSALS", {"verbose": True, "goals": ("G1",)})
    assert a == b and a is not None
    assert canonical_params("PROPOSALS", {}) == ()
    # Cache-busting parameters disable the whole identity.
    assert canonical_params(
        "PROPOSALS", {"ignore_proposal_cache": True}) is None
    assert canonical_params("COMPARE_FUTURES", {"what_if": True}) is None
    assert canonical_params(
        "PROPOSALS", {"ignore_proposal_cache": False}) is not None
    # Endpoint scoping: LOAD coalesces but is not in the cacheable set;
    # mutating endpoints are in neither.
    assert canonical_params("LOAD", {}) == ()
    assert canonical_params("LOAD", {}, allowed=CACHEABLE_ENDPOINTS) is None
    assert canonical_params("REBALANCE", {}) is None


def test_response_cache_lru_and_counters():
    cache = ResponseCache(max_entries=2)
    k1 = ("c", "PROPOSALS", (), 1, ("G",))
    k2 = ("c", "PROPOSALS", (("verbose", "True"),), 1, ("G",))
    k3 = ("c", "COMPARE_FUTURES", (), 1, ("G",))
    assert cache.get(k1) is None
    cache.put(k1, {"v": 1})
    cache.put(k2, {"v": 2})
    assert cache.get(k1) == {"v": 1}
    cache.put(k3, {"v": 3})            # evicts k2 (LRU; k1 was touched)
    assert cache.get(k2) is None
    assert cache.get(k1) == {"v": 1}
    assert cache.stats()["entries"] == 2
    assert cache.hits == 2 and cache.misses == 2
    cache.put(None, {"v": 9})          # None key is a no-op
    cache.put(k1, "not-a-dict")        # non-dict body is a no-op
    assert cache.get(k1) == {"v": 1}
    assert cache.hits == 3
    cache.invalidate()
    assert cache.get(k1) is None
    disabled = ResponseCache(enabled=False)
    disabled.put(k1, {"v": 1})
    assert disabled.get(k1) is None
    assert disabled.hits == 0 and disabled.misses == 0


def test_admission_controller_sheds_past_depth_bound():
    adm = AdmissionController(viewer_max=4, solver_max=2)
    adm.admit(TaskClass.SOLVER, 1, 2.0)      # below bound: admitted
    with pytest.raises(AdmissionShedError) as exc:
        adm.admit(TaskClass.SOLVER, 2, 2.0)  # at bound: shed
    assert exc.value.retry_after_s == pytest.approx(2.0)
    with pytest.raises(AdmissionShedError) as exc:
        adm.admit(TaskClass.SOLVER, 5, 2.0)  # deeper: longer horizon
    assert exc.value.retry_after_s == pytest.approx(8.0)
    assert adm.shed[TaskClass.SOLVER] == 2
    assert adm.stats()["shed"]["SOLVER"] == 2
    adm.admit(TaskClass.VIEWER, 3, 0.05)
    off = AdmissionController(solver_max=0, enabled=False)
    off.admit(TaskClass.SOLVER, 100, 2.0)    # disabled: always admits


# ---- coalescing (UserTaskManager unit) -----------------------------------

def test_user_task_manager_coalesces_identical_inflight_requests():
    engine = AsyncTaskEngine(viewer_threads=1, solver_threads=1)
    mgr = UserTaskManager(engine=engine)
    try:
        ev = threading.Event()
        key = ("c", "PROPOSALS", (), 7, ("G",))

        def slow():
            ev.wait(_WAIT_S)
            return {"answer": 42}

        def never():
            raise AssertionError("joiner work must not run")

        leader = mgr.get_or_create_task("PROPOSALS", "q=1", slow,
                                        client="alice", coalesce_key=key)
        assert mgr.has_inflight(key)
        joiner = mgr.get_or_create_task("PROPOSALS", "q=1", never,
                                        client="bob", coalesce_key=key)
        assert joiner.task_id != leader.task_id
        assert joiner.future is leader.future
        assert joiner.engine_task is leader.engine_task
        assert mgr.coalesced == 1
        # Capability tokens stay session-bound: bob cannot poll alice's id.
        with pytest.raises(TaskOwnershipError):
            mgr.get_or_create_task("PROPOSALS", "q=1", never,
                                   task_id=leader.task_id, client="bob")
        ev.set()
        assert leader.future.result(timeout=_WAIT_S) == {"answer": 42}
        assert joiner.future.result(timeout=0) == {"answer": 42}
        # Completed solves never coalesce: the next identical request is
        # fresh work (the generation may have moved).
        after = mgr.get_or_create_task("PROPOSALS", "q=1",
                                       lambda: {"answer": 43},
                                       client="carol", coalesce_key=key)
        assert after.future is not leader.future
        assert after.future.result(timeout=_WAIT_S) == {"answer": 43}
        assert not mgr.has_inflight(key)
    finally:
        engine.shutdown()


# ---- loadgen -------------------------------------------------------------

def test_loadgen_schedule_is_pure_in_the_seed():
    profile = loadgen.mixed_profile()
    s1 = loadgen.generate_schedule(profile, seed=0, rate_rps=50.0,
                                   duration_s=2.0)
    s2 = loadgen.generate_schedule(profile, seed=0, rate_rps=50.0,
                                   duration_s=2.0)
    assert s1 == s2
    # The digest pinned in bench_baseline.json: crc32 counter-mode means
    # this value is stable across platforms and Python versions.
    assert loadgen.schedule_digest(s1) == "3318f2f9"
    assert len(s1) == 107
    s3 = loadgen.generate_schedule(profile, seed=1, rate_rps=50.0,
                                   duration_s=2.0)
    assert loadgen.schedule_digest(s3) != loadgen.schedule_digest(s1)
    ts = [r.at_s for r in s1]
    assert ts == sorted(ts) and 0.0 < ts[0] and ts[-1] < 2.0
    names = {r.spec.name for r in s1}
    assert "state" in names and "proposals" in names


def test_loadgen_profile_per_cluster():
    profile = loadgen.mixed_profile(["alpha", "beta"])
    assert len(profile) == 12
    byname = {s.name: s for s in profile}
    assert byname["proposals:alpha"].query == "cluster=alpha"
    assert byname["proposals_verbose:beta"].query == \
        "cluster=beta&verbose=true"
    assert byname["proposals:alpha"].klass == "SOLVER"
    assert byname["state:beta"].klass == "VIEWER"


class _StubApi:
    """Deterministic stand-in transport: viewer paths answer 200,
    proposals shed 429 + Retry-After."""

    def handle(self, method, path, query, headers, remote):
        if "proposals" in path:
            return 429, {"errorMessage": "shed"}, {"Retry-After": "2"}
        return 200, {"version": 1, "path": path, "query": query}, {}


def test_loadgen_run_schedule_report_and_slo_judgement():
    profile = loadgen.mixed_profile()
    schedule = loadgen.generate_schedule(profile, seed=3, rate_rps=40.0,
                                         duration_s=1.5)
    report = loadgen.run_schedule(_StubApi(), schedule, concurrency=4)
    n_solver = sum(1 for r in schedule if r.spec.klass == "SOLVER")
    assert report.requests == len(schedule)
    assert report.schedule_digest == loadgen.schedule_digest(schedule)
    assert report.shed == n_solver
    assert report.shed_with_retry_after == n_solver
    assert report.by_status == {200: len(schedule) - n_solver,
                                429: n_solver}
    assert set(report.by_class) == {"VIEWER", "SOLVER"}
    assert report.by_class["VIEWER"]["count"] == len(schedule) - n_solver
    # The stub is deterministic, so each spec has exactly one 200 digest.
    assert all(len(d) == 1 for d in report.digests.values())
    assert report.throughput_rps > 0
    d = report.to_dict()
    assert d["shed"] == n_solver and "by_class" in d
    # SLO judgement: the report passes its own bands and flips on
    # impossible ones.
    assert loadgen.slo_violations(report, {
        "min_shed": 1, "require_retry_after": True,
        "max_error_rate": 0.0}) == []
    flips = loadgen.slo_violations(report, {
        "max_p99_s": {"VIEWER": 0.0},
        "min_throughput_rps": 1e12,
        "max_shed_rate": 0.0,
    })
    assert len(flips) == 3
    assert any("p99" in f for f in flips)
    assert any("throughput" in f for f in flips)
    assert any("shed rate" in f for f in flips)


# ---- end-to-end through the REAL api -------------------------------------

_CAPS = StaticCapacityResolver({}, {Resource.CPU: 100.0, Resource.DISK: 1e7,
                                    Resource.NW_IN: 1e6, Resource.NW_OUT: 1e6})


def _partitions(brokers=(0, 1, 2, 3), topics=2, parts=6):
    out = {}
    for t in range(topics):
        for p in range(parts):
            reps = (brokers[0], brokers[1 + (t + p) % (len(brokers) - 1)])
            out[(f"t{t}", p)] = PartitionState(f"t{t}", p, reps, reps[0],
                                               isr=reps)
    return out


_G = "cruise_control_tpu.analyzer.goals"
# Serving tests exercise the front door (cache/coalesce/admission), not
# the goal chain — a short chain keeps the two per-shape compiles cheap.
# bench.py --serving runs the full default chain.
_SHORT_CHAIN = [f"{_G}.RackAwareGoal", f"{_G}.ReplicaCapacityGoal",
                f"{_G}.ReplicaDistributionGoal"]


def _base_config(extra=None):
    return CruiseControlConfig({
        "goals": _SHORT_CHAIN,
        "hard.goals": [f"{_G}.RackAwareGoal", f"{_G}.ReplicaCapacityGoal"],
        "anomaly.detection.goals": _SHORT_CHAIN,
        "partition.metrics.window.ms": 1000,
        "num.partition.metrics.windows": 3,
        "min.valid.partition.ratio": 0.0,
        "max.solver.rounds": 30,
        "failed.brokers.file.path": "",
        "solver.partition.bucket.size": 0,
        "solver.broker.bucket.size": 0,
        "fleet.bucket.broker.base": 4,
        "fleet.bucket.partition.base": 16,
        **(extra or {})})


def _make_cc(config, partitions, optimizer=None):
    backend = InMemoryAdminBackend(partitions.values())
    monitor = LoadMonitor(config, backend, samplers=[SyntheticSampler()],
                          capacity_resolver=_CAPS)
    cc = CruiseControl(config, backend, load_monitor=monitor,
                       executor=Executor(backend, synchronous=True))
    for k in range(1, 4):
        monitor.task_runner.run_sampling_once(end_ms=k * 1000)
    return cc


@pytest.fixture(scope="module")
def fleet_api():
    """Two clusters at two DIFFERENT bucket shapes sharing one api:
    alpha pads to (8, 64), gamma to (4, 16) — the cache byte-identity
    claim is pinned at both shapes."""
    base = _base_config()
    scheduler = FleetScheduler(starvation_bound_s=30.0)
    registry = FleetRegistry(base_config=base, scheduler=scheduler)
    registry.register("alpha", cc=_make_cc(
        base, _partitions(tuple(range(8)), topics=2, parts=17)))
    registry.register("gamma", cc=_make_cc(
        base, _partitions((0, 1, 2, 3), topics=2, parts=6)))
    api = CruiseControlApi(registry.get("alpha"), fleet=registry)
    api._async_wait_s = 180
    yield api, registry
    api.shutdown()
    scheduler.shutdown()


def test_cache_hit_is_byte_identical_at_two_bucket_shapes(fleet_api):
    api, _registry = fleet_api
    api.response_cache.invalidate()
    for cid in ("alpha", "gamma"):
        tasks_before = len(api._tasks.all_tasks())
        s1, b1, h1 = api.handle("GET", "/kafkacruisecontrol/proposals",
                                f"cluster={cid}")
        assert s1 == 200, b1
        assert "X-Serving-Cache" not in h1
        s2, b2, h2 = api.handle("GET", "/kafkacruisecontrol/proposals",
                                f"cluster={cid}")
        assert s2 == 200
        assert h2.get("X-Serving-Cache") == "hit"
        assert json.dumps(b1, sort_keys=True) == \
            json.dumps(b2, sort_keys=True)
        # A hit never creates a task (no queue slot, no solver time).
        assert len(api._tasks.all_tasks()) == tasks_before + 1
    assert api.response_cache.hits >= 2


def test_parallel_requests_byte_identical_to_serial(fleet_api):
    api, _registry = fleet_api
    api.response_cache.invalidate()
    s0, solo, _ = api.handle("GET", "/kafkacruisecontrol/proposals",
                             "cluster=alpha")
    assert s0 == 200, solo
    want = json.dumps(solo, sort_keys=True)
    results = [None] * 6

    def worker(i):
        results[i] = api.handle("GET", "/kafkacruisecontrol/proposals",
                                "cluster=alpha")

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(len(results))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=_WAIT_S * 6)
    for status, body, _hdrs in results:
        assert status == 200
        assert json.dumps(body, sort_keys=True) == want


def test_cache_busting_param_skips_the_cache(fleet_api):
    api, _registry = fleet_api
    api.response_cache.invalidate()
    api.handle("GET", "/kafkacruisecontrol/proposals", "cluster=gamma")
    _s, _b, h = api.handle("GET", "/kafkacruisecontrol/proposals",
                           "cluster=gamma&ignore_proposal_cache=true")
    assert "X-Serving-Cache" not in h


def test_user_tasks_surface_engine_lifecycle(fleet_api):
    api, _registry = fleet_api
    s, _body, _h = api.handle("GET", "/kafkacruisecontrol/load",
                              "cluster=gamma")
    assert s == 200
    s2, tasks, _h2 = api.handle("GET", "/kafkacruisecontrol/user_tasks")
    assert s2 == 200
    rows = [t for t in tasks["userTasks"]
            if t.get("TaskLifecycle") is not None]
    assert rows, tasks
    assert any(t["TaskLifecycle"] == "done" and t["TaskClass"] == "VIEWER"
               for t in rows)


@pytest.fixture(scope="module")
def solo_api():
    cfg = _base_config()
    cc = _make_cc(cfg, _partitions())
    api = CruiseControlApi(cc)
    api._async_wait_s = 180
    yield api, cc
    api.shutdown()


def test_identical_inflight_request_attaches_through_dispatch(solo_api):
    """A real request arriving while an identical solve is in flight
    coalesces: it returns the LEADER's body under its OWN task id."""
    api, cc = solo_api
    api.response_cache.invalidate()
    identity = CruiseControlApi._response_identity(cc, None)
    assert identity is not None
    key = (None, "PROPOSALS", canonical_params("PROPOSALS", {}), *identity)
    ev = threading.Event()
    sentinel = {"version": 1, "sentinel": True}

    def slow():
        ev.wait(_WAIT_S)
        return sentinel

    before = api._tasks.coalesced
    leader = api._tasks.get_or_create_task(
        "PROPOSALS", "", slow, client="someone-else", coalesce_key=key)
    out = {}

    def request():
        out["r"] = api.handle("GET", "/kafkacruisecontrol/proposals")

    t = threading.Thread(target=request, daemon=True)
    t.start()
    assert _poll(lambda: api._tasks.coalesced > before)
    ev.set()
    t.join(timeout=_WAIT_S)
    assert not t.is_alive()
    status, body, hdrs = out["r"]
    assert status == 200
    assert body == sentinel
    assert hdrs[USER_TASK_HEADER] != leader.task_id
    # The joiner's own id polls the shared result; the sentinel never
    # entered the response cache (only the joiner's discarded closure
    # would have stored it).
    s2, b2, _ = api.handle("GET", "/kafkacruisecontrol/proposals", "",
                           {USER_TASK_HEADER: hdrs[USER_TASK_HEADER]})
    assert s2 == 200 and b2 == sentinel
    s3, _b3, h3 = api.handle("GET", "/kafkacruisecontrol/proposals")
    assert s3 == 200 and "X-Serving-Cache" not in h3


@pytest.fixture(scope="module")
def overloaded_api():
    """Solver admission bound of zero: every NEW solver request sheds
    immediately while viewer traffic keeps flowing."""
    cfg = _base_config({"serving.admission.queue.solver.max": 0,
                        "serving.coalesce.enabled": False,
                        "serving.cache.enabled": False})
    cc = _make_cc(cfg, _partitions())
    api = CruiseControlApi(cc)
    api._async_wait_s = 180
    yield api
    api.shutdown()


def test_overload_sheds_solver_class_with_retry_after(overloaded_api):
    api = overloaded_api
    status, body, headers = api.handle(
        "GET", "/kafkacruisecontrol/proposals")
    assert status == 429
    assert "shed" in body["errorMessage"]
    assert int(headers["Retry-After"]) >= 1
    # Viewer classes are untouched by the solver bound.
    assert api.handle("GET", "/kafkacruisecontrol/load")[0] == 200
    assert api.handle("GET", "/kafkacruisecontrol/state")[0] == 200
    assert api.admission.stats()["shed"]["SOLVER"] >= 1


def test_loadgen_overload_arm_against_real_api(overloaded_api):
    api = overloaded_api
    schedule = loadgen.generate_schedule(loadgen.mixed_profile(), seed=5,
                                         rate_rps=30.0, duration_s=1.0)
    report = loadgen.run_schedule(api, schedule, concurrency=4)
    assert report.requests == len(schedule)
    assert report.shed >= 1
    assert report.shed_with_retry_after == report.shed
    assert set(report.by_status) <= {200, 429}
    assert loadgen.slo_violations(report, {
        "min_shed": 1, "require_retry_after": True,
        "max_error_rate": 0.0}) == []
