"""Analyzer invariant tests.

Mirrors the reference's OptimizationVerifier.java strategy (§4 of
SURVEY.md): run a goal list on deterministic + randomized clusters and
assert INVARIANTS (hard goals satisfied, offline replicas moved, no
regression), not golden outputs.
"""

import numpy as np
import pytest

from cruise_control_tpu.analyzer import (
    BalancingConstraint, ExclusionMasks, GoalOptimizer, OptimizationFailureError,
    OptimizationOptions, SearchConfig, diff_proposals, optimize_goal,
)
from cruise_control_tpu.analyzer.goals import (
    CpuCapacityGoal, DiskCapacityGoal, LeaderReplicaDistributionGoal,
    NetworkInboundCapacityGoal, NetworkOutboundCapacityGoal, RackAwareGoal,
    ReplicaCapacityGoal, ReplicaDistributionGoal,
)
from cruise_control_tpu.analyzer.optimizer import balancedness_score, goals_by_priority
from cruise_control_tpu.common import Resource
from cruise_control_tpu.config import CruiseControlConfig
from cruise_control_tpu.model import (
    broker_load, broker_replica_counts, fixtures, offline_replicas,
    rack_partition_counts,
)
from cruise_control_tpu.model.tensors import replica_exists

FAST = SearchConfig(num_sources=32, num_dests=8, moves_per_round=16, max_rounds=60)


def run_goal(state, goal, num_topics, optimized=(), constraint=None):
    return optimize_goal(state, goal, optimized, constraint or BalancingConstraint(),
                         FAST, num_topics, ExclusionMasks())


def test_rack_aware_fixes_satisfiable():
    state, meta = fixtures.rack_aware_satisfiable()
    final, info = run_goal(state, RackAwareGoal(), meta.num_topics)
    counts = np.asarray(rack_partition_counts(final, len(meta.rack_names)))
    live = np.asarray(final.partition_mask)
    assert (counts[live] <= 1).all(), counts
    assert info["succeeded"]


def test_rack_aware_unsatisfiable_raises():
    state, meta = fixtures.rack_aware_unsatisfiable()
    with pytest.raises(OptimizationFailureError):
        run_goal(state, RackAwareGoal(), meta.num_topics)


def test_replica_distribution_balances():
    state, meta = fixtures.small_unbalanced(num_brokers=3)
    final, info = run_goal(state, ReplicaDistributionGoal(), meta.num_topics)
    counts = np.asarray(broker_replica_counts(final))[:3]
    # 16 replicas over 3 brokers within ceil/floor band of threshold 1.1:
    # avg 5.33 -> [4, 6].
    assert counts.max() <= 6 and counts.min() >= 4, counts
    assert info["succeeded"]


def test_capacity_goal_respects_limit():
    state, meta = fixtures.small_unbalanced()
    final, info = run_goal(state, CpuCapacityGoal(), meta.num_topics)
    load = np.asarray(broker_load(final))[:, Resource.CPU]
    limit = 0.7 * 100.0
    assert (load <= limit + 1e-4).all(), load
    assert info["succeeded"]


def test_self_healing_moves_offline_replicas():
    state, meta = fixtures.dead_broker_cluster()
    assert int(np.asarray(offline_replicas(state)).sum()) == 4
    final, info = run_goal(state, ReplicaDistributionGoal(), meta.num_topics)
    assert info["offline_remaining"] == 0
    # Load conservation: nothing lost, everything lives on alive brokers.
    reps = np.asarray(broker_replica_counts(final))
    assert reps.sum() == 8
    assert reps[3] == 0  # dead broker drained


def test_hard_goal_chain_on_random_cluster():
    state, meta = fixtures.random_cluster(num_brokers=12, num_topics=6,
                                          num_partitions=120, rf=3, seed=3,
                                          skew_to_first=2.5)
    cfg = CruiseControlConfig()
    goals = goals_by_priority(cfg)[:6]  # the six hard goals
    constraint = BalancingConstraint.from_config(cfg)
    s = state
    optimized = []
    for g in goals:
        s, info = optimize_goal(s, g, tuple(optimized), constraint, FAST,
                                meta.num_topics, ExclusionMasks())
        optimized.append(g)
    # All hard constraints hold at the end (later goals never broke earlier
    # ones thanks to the acceptance stack).
    load = np.asarray(broker_load(s))
    cap = np.asarray(s.capacity)
    for r, thresh in ((Resource.DISK, 0.8), (Resource.NW_IN, 0.8),
                      (Resource.NW_OUT, 0.8), (Resource.CPU, 0.7)):
        assert (load[:12, r] <= thresh * cap[:12, r] + 1e-3).all(), (r, load[:, r])
    counts = np.asarray(rack_partition_counts(s, len(meta.rack_names)))
    assert (counts[np.asarray(s.partition_mask)] <= 1).all()


def test_optimizer_end_to_end_improves_balancedness():
    state, meta = fixtures.random_cluster(num_brokers=8, num_topics=4,
                                          num_partitions=60, rf=2, seed=11,
                                          skew_to_first=3.0)
    cfg = CruiseControlConfig({"max.solver.rounds": 40,
                               "solver.moves.per.round": 16})
    opt = GoalOptimizer(cfg)
    final, res = opt.optimizations(state, meta)
    assert res.balancedness_after >= res.balancedness_before
    # Hard goals must all be satisfied.
    hard_after = [g for g in res.violated_goals_after
                  if any(r.name == g and r.is_hard for r in res.goal_results)]
    assert hard_after == []
    # Proposals describe real changes only.
    for p in res.proposals:
        assert p.old_replicas != p.new_replicas or p.old_leader != p.new_leader


def test_proposal_diff_roundtrip():
    state, meta = fixtures.small_unbalanced()
    final, _ = run_goal(state, ReplicaDistributionGoal(), meta.num_topics)
    proposals = diff_proposals(state, final, meta)
    assert proposals  # the unbalanced fixture must produce moves
    moved = {(p.topic, p.partition) for p in proposals}
    a0 = np.asarray(state.assignment)
    a1 = np.asarray(final.assignment)
    l0, l1 = np.asarray(state.leader_slot), np.asarray(final.leader_slot)
    for i, (t, pn) in enumerate(meta.partition_index):
        changed = (a0[i] != a1[i]).any() or l0[i] != l1[i]
        assert changed == ((t, pn) in moved)
    # Replica sets in proposals are consistent with the model.
    for p in proposals:
        assert len(set(p.new_replicas)) == len(p.new_replicas)
        assert p.new_leader in p.new_replicas


def test_excluded_topics_not_moved():
    state, meta = fixtures.small_unbalanced()
    opt = GoalOptimizer(CruiseControlConfig({"max.solver.rounds": 30,
                                             "solver.moves.per.round": 8}))
    final, res = opt.optimizations(
        state, meta, goals=[ReplicaDistributionGoal()],
        options=OptimizationOptions(excluded_topics=("t1",)))
    for p in res.proposals:
        assert p.topic != "t1"


def test_balancedness_score_monotone():
    goals = goals_by_priority(CruiseControlConfig())
    all_names = {g.name for g in goals}
    assert balancedness_score(goals, set()) == pytest.approx(100.0)
    assert balancedness_score(goals, all_names) == pytest.approx(0.0)
    partial = balancedness_score(goals, {"ReplicaDistributionGoal"})
    assert 0 < partial < 100


def test_preferred_leader_election_converges():
    from cruise_control_tpu.analyzer.goals import PreferredLeaderElectionGoal
    from cruise_control_tpu.model import ClusterModelBuilder
    b = ClusterModelBuilder()
    cap = {Resource.CPU: 100.0, Resource.NW_IN: 1000.0, Resource.NW_OUT: 1000.0,
           Resource.DISK: 10000.0}
    b.add_broker(0, "rA", cap).add_broker(1, "rB", cap).add_broker(2, "rC", cap)
    load = {Resource.CPU: 5.0, Resource.NW_OUT: 20.0}
    b.add_partition("t", 0, [0, 1], leader_load=load, leader_index=1)
    b.add_partition("t", 1, [1, 2], leader_load=load, leader_index=1)
    b.add_partition("t", 2, [2, 0], leader_load=load, leader_index=0)
    state, meta = b.build()
    final, info = run_goal(state, PreferredLeaderElectionGoal(), meta.num_topics)
    assert np.asarray(final.leader_slot)[:3].tolist() == [0, 0, 0]
    assert info["succeeded"]
    assert info["rounds"] <= 5  # must not churn


def test_no_phantom_replicas_after_optimization():
    state, meta = fixtures.random_cluster(num_brokers=6, num_topics=3,
                                          num_partitions=40, rf=2, seed=5)
    final, _ = run_goal(state, ReplicaDistributionGoal(), meta.num_topics)
    # Same number of replicas per partition; no duplicates within a partition.
    e0 = np.asarray(replica_exists(state)).sum(axis=1)
    e1 = np.asarray(replica_exists(final)).sum(axis=1)
    np.testing.assert_array_equal(e0, e1)
    a1 = np.asarray(final.assignment)
    for row in a1[np.asarray(final.partition_mask)]:
        live = row[row >= 0]
        assert len(set(live.tolist())) == len(live)


def test_broker_set_aware_goal_confines_topics():
    from cruise_control_tpu.analyzer.goals import BrokerSetAwareGoal
    from cruise_control_tpu.model.builder import ClusterModelBuilder

    cap = {Resource.CPU: 100.0, Resource.NW_IN: 1e5, Resource.NW_OUT: 1e5,
           Resource.DISK: 1e6}
    load = {Resource.CPU: 1.0, Resource.NW_IN: 10.0, Resource.NW_OUT: 10.0,
            Resource.DISK: 100.0}
    b = ClusterModelBuilder()
    for i in range(4):
        b.add_broker(i, f"r{i}", cap)
    # Topic tA lives mostly in set 0 (brokers 0,1) with one stray replica on
    # broker 3 (set 1); topic tB mostly set 1 with a stray on broker 0.
    b.add_partition("tA", 0, [0, 1], leader_load=load)
    b.add_partition("tA", 1, [1, 3], leader_load=load)
    b.add_partition("tA", 2, [0, 1], leader_load=load)
    b.add_partition("tB", 0, [2, 3], leader_load=load)
    b.add_partition("tB", 1, [3, 0], leader_load=load)
    b.add_partition("tB", 2, [2, 3], leader_load=load)
    state, meta = b.build()
    goal = BrokerSetAwareGoal(broker_sets=(0, 0, 1, 1))
    final, info = run_goal(state, goal, meta.num_topics)
    assert info["succeeded"]
    assign = np.asarray(final.assignment)
    sets = np.array([0, 0, 1, 1])
    for p_idx, (topic, _p) in enumerate(meta.partition_index):
        placed = [sets[b] for b in assign[p_idx] if b >= 0]
        want = 0 if topic == "tA" else 1
        assert all(s == want for s in placed), (topic, placed)


def test_kafka_assigner_even_rack_aware_goal():
    from cruise_control_tpu.analyzer.goals import KafkaAssignerEvenRackAwareGoal
    state, meta = fixtures.random_cluster(
        num_brokers=6, num_topics=3, num_partitions=24, rf=2, num_racks=3,
        seed=3, skew_to_first=2.0)
    final, info = run_goal(state, KafkaAssignerEvenRackAwareGoal(),
                           meta.num_topics)
    counts = np.asarray(rack_partition_counts(final, len(meta.rack_names)))
    live = np.asarray(final.partition_mask)
    assert (counts[live] <= 1).all(), "rack-awareness must hold"
    reps = np.asarray(broker_replica_counts(final))[:6]
    total = reps.sum()
    assert reps.max() <= int(np.ceil(total / 6)) + 1, reps


def test_kafka_assigner_even_rack_deadlock_fixture():
    """Regression: on a skewed fixture where every under-ceiling broker in
    a partition's free rack sits at the even ceiling, a pure greedy stalls
    (hard-goal failure). Duplicate-fixing moves may overshoot the ceiling
    by one (then shed), matching the reference's swap-based inner loop's
    reachability (analyzer/kafkaassigner/KafkaAssignerEvenRackAwareGoal
    .java)."""
    from cruise_control_tpu.analyzer.optimizer import (
        GoalOptimizer, goals_by_priority,
    )
    from cruise_control_tpu.config.cruise_control_config import (
        CruiseControlConfig,
    )
    cfg = CruiseControlConfig()
    state, meta = fixtures.random_cluster(
        num_brokers=24, num_topics=8, num_partitions=768, rf=3, num_racks=4,
        dist=fixtures.Dist.EXPONENTIAL, seed=11, target_utilization=0.55)
    opt = GoalOptimizer(cfg)
    final, res = opt.optimizations(state, meta, goals=goals_by_priority(
        cfg, ["KafkaAssignerEvenRackAwareGoal",
              "KafkaAssignerDiskUsageDistributionGoal"]))
    assert res.violated_goals_after == []
    counts = np.asarray(rack_partition_counts(final, len(meta.rack_names)))
    live = np.asarray(final.partition_mask)
    assert (counts[live] <= 1).all(), "rack-awareness must hold"
    reps = np.asarray(broker_replica_counts(final))[:24]
    assert reps.max() <= int(np.ceil(reps.sum() / 24)), reps


def test_kafka_assigner_disk_goal_balances_disk():
    from cruise_control_tpu.analyzer.goals import (
        KafkaAssignerDiskUsageDistributionGoal,
    )
    state, meta = fixtures.random_cluster(
        num_brokers=5, num_topics=2, num_partitions=40, rf=2, num_racks=2,
        seed=5, skew_to_first=3.0, target_utilization=0.5)
    goal = KafkaAssignerDiskUsageDistributionGoal()
    before = np.asarray(broker_load(state))[:, int(Resource.DISK)]
    final, info = run_goal(state, goal, meta.num_topics)
    after = np.asarray(broker_load(final))[:, int(Resource.DISK)]
    assert after.std() < before.std(), (before, after)


def test_swap_phase_balances_when_moves_cannot():
    """Swap parity (AbstractGoal.maybeApplySwapAction:287): replica-count
    capacity pins every broker at its replica cap, so no plain move is
    possible — only swaps can equalize disk load."""
    from cruise_control_tpu.analyzer.goals import DiskUsageDistributionGoal
    from cruise_control_tpu.model.builder import ClusterModelBuilder

    cap = {Resource.CPU: 100.0, Resource.NW_IN: 1e6, Resource.NW_OUT: 1e6,
           Resource.DISK: 1e6}
    b = ClusterModelBuilder()
    b.add_broker(0, "rA", cap).add_broker(1, "rB", cap)
    # Broker 0 hosts 4 heavy partitions, broker 1 hosts 4 light ones.
    for p in range(4):
        b.add_partition("heavy", p, [0], leader_load={
            Resource.CPU: 1.0, Resource.NW_IN: 10.0, Resource.NW_OUT: 10.0,
            Resource.DISK: 200.0})
    for p in range(4):
        b.add_partition("light", p, [1], leader_load={
            Resource.CPU: 1.0, Resource.NW_IN: 10.0, Resource.NW_OUT: 10.0,
            Resource.DISK: 50.0})
    state, meta = b.build()
    goal = DiskUsageDistributionGoal()
    # A replica cap of 4 per broker blocks every move; swaps keep counts.
    constraint = BalancingConstraint(max_replicas_per_broker=4)
    prior = (ReplicaCapacityGoal(),)
    before = np.asarray(broker_load(state))[:2, int(Resource.DISK)]
    final, info = run_goal(state, goal, meta.num_topics, optimized=prior,
                           constraint=constraint)
    after = np.asarray(broker_load(final))[:2, int(Resource.DISK)]
    counts = np.asarray(broker_replica_counts(final))[:2]
    assert (counts == 4).all(), counts
    assert info["swaps_applied"] > 0, info
    assert abs(after[0] - after[1]) < abs(before[0] - before[1]), (before, after)


def test_optimizer_resolves_broker_sets_from_config():
    """GoalOptimizer must bind broker→set ids into a bare BrokerSetAwareGoal
    from the configured mapping policy / brokerSets.json, and fail loud when
    neither resolves (a vacuous broker-set constraint must be impossible)."""
    import json as json_mod
    import os as os_mod
    import tempfile

    import pytest as _pytest

    from cruise_control_tpu.analyzer.goals import (
        BrokerSetAwareGoal, RackAwareGoal,
    )
    from cruise_control_tpu.analyzer.optimizer import GoalOptimizer
    from cruise_control_tpu.config.cruise_control_config import (
        CruiseControlConfig,
    )
    from cruise_control_tpu.model.fixtures import random_cluster

    state, meta = random_cluster(num_brokers=4, num_topics=2,
                                 num_partitions=16, rf=2, num_racks=2, seed=0)
    with tempfile.NamedTemporaryFile("w", suffix=".json",
                                     delete=False) as f:
        json_mod.dump({"brokerSets": [
            {"brokerSetId": "a", "brokerIds": meta.broker_ids[:2]},
            {"brokerSetId": "b", "brokerIds": meta.broker_ids[2:]}]}, f)
        path = f.name
    try:
        cfg = CruiseControlConfig({"broker.set.config.file": path})
        opt = GoalOptimizer(cfg)
        chain = opt._resolve_broker_sets(
            [RackAwareGoal(), BrokerSetAwareGoal()], meta)
    finally:
        os_mod.unlink(path)
    assert chain[1].broker_sets == (0, 0, 1, 1)
    assert isinstance(chain[0], RackAwareGoal)       # others untouched
    # A goal that already carries sets is left alone.
    pre = BrokerSetAwareGoal(broker_sets=(1, 1, 0, 0))
    assert opt._resolve_broker_sets([pre], meta)[0].broker_sets == (1, 1, 0, 0)
    # No mapping resolvable -> loud failure, not a vacuous constraint.
    cfg_missing = CruiseControlConfig(
        {"broker.set.config.file": "/nonexistent/brokerSets.json"})
    with _pytest.raises(ValueError, match="broker-set mapping"):
        GoalOptimizer(cfg_missing)._resolve_broker_sets(
            [BrokerSetAwareGoal()], meta)
    # A pluggable mapping policy wins over the file.
    cfg_policy = CruiseControlConfig({
        "replica.to.broker.set.mapping.policy.class":
            "tests.test_analyzer.modulo_broker_sets"})
    chain = GoalOptimizer(cfg_policy)._resolve_broker_sets(
        [BrokerSetAwareGoal()], meta)
    assert chain[0].broker_sets == (0, 1, 0, 1)


def modulo_broker_sets(_config, broker_ids):
    """Test mapping policy plugin (replica.to.broker.set.mapping.policy.class)."""
    return tuple(i % 2 for i in range(len(broker_ids)))
