"""Direct-assignment transport kernels (analyzer/direct.py, round 17;
sparse-aware fractional plan round 21).

The load-bearing contracts:

- **Transport invariants**: final per-broker / per-topic counts land
  inside the goal's band (targets hit exactly on feasible instances),
  no RF-sibling colocation is ever created, rack-awareness and
  exclusion masks are respected, and the plan is byte-deterministic.
- **Sparse regime**: the fractional-target plan serves sparse cell
  geometries the retired density gate used to refuse — the rounding
  PRNG is crc32-seeded and trace-time static (CCSA004).
- **Below-gate parity**: with the kernel enabled but the cluster below
  ``solver.wide.batch.min.brokers``, the optimizer's trajectory is
  byte-identical to the disabled path (the greedy byte-parity pins
  keep holding).
- **Megabatch composition**: a direct solve on a partially-filled batch
  leaves inert pad slots byte-frozen, matches the solo solve per
  cluster, and occupancy stays traced (one compiled program per shape).
- **Telemetry**: direct dispatches record as their own
  ``kind="direct"`` series, stay OUT of the acceptance-density
  histogram, and label the goal's solve mode.
"""

import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

from cruise_control_tpu.analyzer.chain import (
    DispatchStats, MegastepConfig, inert_state_like, optimize_goal_in_chain,
    stack_states, unstack_state,
)
from cruise_control_tpu.analyzer.constraint import BalancingConstraint
from cruise_control_tpu.analyzer.derived import compute_derived, count_limits
from cruise_control_tpu.analyzer.direct import (
    direct_eligible, direct_transport_rounds, megabatch_direct_rounds,
    run_direct_pass,
)
from cruise_control_tpu.analyzer.goals import (
    LeaderBytesInDistributionGoal, LeaderReplicaDistributionGoal,
    NetworkOutboundUsageDistributionGoal, PreferredLeaderElectionGoal,
    RackAwareGoal, ReplicaCapacityGoal, ReplicaDistributionGoal,
    TopicReplicaDistributionGoal,
)
from cruise_control_tpu.analyzer.search import ExclusionMasks, SearchConfig
from cruise_control_tpu.model.fixtures import random_cluster
from cruise_control_tpu.model.tensors import (
    broker_leader_counts, broker_replica_counts, replica_exists,
    topic_broker_replica_counts,
)

CHAIN = (RackAwareGoal(), ReplicaCapacityGoal(),
         NetworkOutboundUsageDistributionGoal(), ReplicaDistributionGoal(),
         TopicReplicaDistributionGoal(), LeaderReplicaDistributionGoal(),
         PreferredLeaderElectionGoal())
REPL_IDX = 3
TR_IDX = 4
LEAD_IDX = 5
CFG = SearchConfig(num_sources=32, num_dests=8, moves_per_round=32,
                   max_rounds=60)
CON = BalancingConstraint()
MASKS = ExclusionMasks()
DIRECT = MegastepConfig(donate=False, async_readback=True,
                        direct_assignment=True, direct_max_sweeps=8)
GREEDY = MegastepConfig(donate=False, async_readback=True)


def _cluster(seed=3, partition_bucket=0):
    return random_cluster(num_brokers=12, num_topics=6, num_partitions=96,
                          rf=2, num_racks=3, seed=seed, skew_to_first=2.0,
                          partition_bucket=partition_bucket)


def _sibling_clean(state) -> bool:
    a = np.asarray(state.assignment)
    for pi in range(a.shape[0]):
        row = a[pi][a[pi] >= 0]
        if len(set(row.tolist())) != len(row):
            return False
    return True


def _rack_duplicates(state) -> int:
    a = np.asarray(state.assignment)
    rack = np.asarray(state.rack)
    dups = 0
    for pi in range(a.shape[0]):
        row = a[pi][a[pi] >= 0]
        rr = rack[row].tolist()
        dups += len(rr) - len(set(rr))
    return dups


def _run_chain(state, meta, mega, masks=MASKS, chain=CHAIN):
    infos = []
    for i in range(len(chain)):
        state, info = optimize_goal_in_chain(
            state, chain, i, CON, CFG, meta.num_topics, masks,
            dispatch_rounds=8, megastep=mega,
            donate_input=bool(infos) and any(
                x["rounds"] > 0 or x.get("direct_sweeps", 0) > 0
                for x in infos))
        infos.append(info)
    return state, infos


def test_direct_eligibility_whitelist():
    """Only the count goals have a transport formulation, and an
    unrecognized prior goal (here LeaderBytesIn) disables the kernel for
    everything stacked after it — the conservative-fallback contract."""
    assert [direct_eligible(CHAIN, i) for i in range(len(CHAIN))] == \
        [False, False, False, True, True, True, False]
    tainted = (LeaderBytesInDistributionGoal(), ReplicaDistributionGoal(),
               TopicReplicaDistributionGoal())
    assert [direct_eligible(tainted, i) for i in range(3)] == \
        [False, False, False]


def test_density_regime_gate_retired():
    """The density gate (``direct_regime_ok``, rounds 17-20) is GONE:
    the sparse-aware fractional plan serves every density regime, so the
    module must not export the gate or its threshold anymore."""
    import cruise_control_tpu.analyzer.direct as direct_mod
    assert not hasattr(direct_mod, "direct_regime_ok")
    assert not hasattr(direct_mod, "MIN_TOPIC_CELL_DENSITY")


def test_sparse_rounding_seed_is_crc32_and_salted():
    """The rounding PRNG seed is the crc32 determinism idiom (CCSA004):
    the module default is the crc32 of the contract string, a salt folds
    in via crc32 XOR at trace time, and the empty salt is the default."""
    import zlib

    from cruise_control_tpu.analyzer.direct import (
        SPARSE_ROUNDING_SEED, sparse_rounding_seed,
    )
    assert SPARSE_ROUNDING_SEED == zlib.crc32(
        b"cruise-control:direct.sparse.rounding")
    assert sparse_rounding_seed() == SPARSE_ROUNDING_SEED
    assert sparse_rounding_seed("") == SPARSE_ROUNDING_SEED
    assert sparse_rounding_seed("fleet-a") == \
        SPARSE_ROUNDING_SEED ^ zlib.crc32(b"fleet-a")
    assert sparse_rounding_seed("fleet-a") != sparse_rounding_seed("fleet-b")


def test_systematic_rounding_preserves_group_totals():
    """Per-group low-discrepancy rounding: every entry rounds to floor
    or ceil, group totals stay within ±1 of the fractional mass, and the
    draw is a pure function of (index, sweep, seed)."""
    from cruise_control_tpu.analyzer.direct import (
        _hash_uniform, _round_systematic,
    )
    x = jnp.asarray(np.random.default_rng(0).uniform(0.0, 3.0, (7, 13)),
                    dtype=jnp.float32)
    u = _hash_uniform(jnp.arange(7), 0, 1234)
    t = np.asarray(_round_systematic(x, u))
    xf = np.asarray(x)
    assert np.all((t == np.floor(xf)) | (t == np.ceil(xf)))
    np.testing.assert_allclose(t.sum(1), xf.sum(1), atol=1.0 + 1e-4)
    t2 = np.asarray(_round_systematic(x, _hash_uniform(jnp.arange(7), 0,
                                                       1234)))
    np.testing.assert_array_equal(t, t2)
    # sweep re-draw rotates the rounding pattern (not byte-frozen)
    u3 = _hash_uniform(jnp.arange(7), 1, 1234)
    assert not np.array_equal(np.asarray(u), np.asarray(u3))


def _sparse_cluster(seed=5):
    # ~1.1 replicas per (topic, broker) cell: the geometry the old
    # density gate refused (1k/100k production shape, scaled down).
    return random_cluster(num_brokers=24, num_topics=48,
                          num_partitions=640, rf=2, num_racks=4, seed=seed,
                          skew_to_first=2.0)


def test_direct_topic_plane_solves_sparse_regime():
    """The tentpole pin: at sparse cell density the topic-plane
    transport now RUNS (the gate is retired) and strictly reduces the
    topic band violation without breaking the prior replica band or
    sibling cleanliness — the failure mode that motivated the old gate
    (plan mis-fit, polish stall) must not reappear."""
    state, meta = _sparse_cluster()
    dens = state.num_partitions * 2 / (meta.num_topics * state.num_brokers)
    assert dens < 1.5, dens
    chain = (RackAwareGoal(), ReplicaCapacityGoal(),
             ReplicaDistributionGoal(), TopicReplicaDistributionGoal())
    st, _m, _s, _pl = direct_transport_rounds(
        state, chain, 2, CON, meta.num_topics, MASKS, 16)
    repl_before = _replica_band_violation(st)
    tr_before = _topic_band_violation(st, meta.num_topics)
    st2, moves, _sw, _pl2 = direct_transport_rounds(
        st, chain, 3, CON, meta.num_topics, MASKS, 16)
    assert int(moves) > 0
    assert _sibling_clean(st2)
    assert _replica_band_violation(st2) <= repl_before + 1e-6
    after = _topic_band_violation(st2, meta.num_topics)
    assert after < tr_before, (after, tr_before)
    # byte-determinism at the sparse geometry (rounding PRNG is static)
    st3, m3, _s3, _pl3 = direct_transport_rounds(
        st, chain, 3, CON, meta.num_topics, MASKS, 16)
    np.testing.assert_array_equal(np.asarray(st2.assignment),
                                  np.asarray(st3.assignment))
    assert int(m3) == int(moves)


def test_direct_sparse_salt_changes_plan_but_not_quality():
    """A rounding salt decorrelates the plan (different mover choice is
    allowed) while keeping every invariant: siblings clean, prior bands
    held, topic violation reduced at least as well as stalled."""
    from cruise_control_tpu.analyzer.direct import sparse_rounding_seed
    state, meta = _sparse_cluster(seed=9)
    chain = (RackAwareGoal(), ReplicaCapacityGoal(),
             ReplicaDistributionGoal(), TopicReplicaDistributionGoal())
    st, _m, _s, _pl = direct_transport_rounds(
        state, chain, 2, CON, meta.num_topics, MASKS, 16)
    before = _topic_band_violation(st, meta.num_topics)
    outs = []
    for salt in ("", "fleet-a"):
        st2, moves, _sw, _pl2 = direct_transport_rounds(
            st, chain, 3, CON, meta.num_topics, MASKS, 16,
            seed=sparse_rounding_seed(salt))
        assert _sibling_clean(st2)
        assert _topic_band_violation(st2, meta.num_topics) <= before
        outs.append((np.asarray(st2.assignment), int(moves)))
    # same salt replays byte-identically (covered above); a different
    # salt must still move work (quality, not bytes, is the contract)
    assert outs[1][1] > 0


def test_direct_replica_counts_hit_target_band():
    """The transport lands every alive broker inside the replica-count
    band (targets hit exactly — residual violation 0 on this feasible
    instance), creates no sibling colocation, and is byte-deterministic
    at a fixed seed."""
    state, meta = _cluster()
    chain = (RackAwareGoal(), ReplicaCapacityGoal(),
             ReplicaDistributionGoal())
    st, moves, sweeps, _pl = direct_transport_rounds(
        state, chain, 2, CON, meta.num_topics, MASKS, 16)
    assert int(moves) > 0
    derived = compute_derived(st)
    lo, up = count_limits(derived.avg_replicas,
                          CON.replica_balance_threshold)
    cnt = np.asarray(broker_replica_counts(st))
    alive = np.asarray(derived.alive)
    viol = np.sum((np.maximum(cnt - float(up), 0)
                   + np.maximum(float(lo) - cnt, 0)) * alive)
    assert viol <= 3.0, (cnt, float(lo), float(up))
    assert _sibling_clean(st)
    st2, m2, s2, _pl2 = direct_transport_rounds(
        state, chain, 2, CON, meta.num_topics, MASKS, 16)
    np.testing.assert_array_equal(np.asarray(st.assignment),
                                  np.asarray(st2.assignment))
    assert int(m2) == int(moves) and int(s2) == int(sweeps)


def test_direct_topic_counts_respect_band_and_priors():
    """The per-topic plane lands inside its band while the prior
    replica-count band is NOT violated by the transport (the dst-cap /
    src-floor guards)."""
    state, meta = _cluster(seed=7)
    chain = (RackAwareGoal(), ReplicaCapacityGoal(),
             ReplicaDistributionGoal(), TopicReplicaDistributionGoal())
    st, _m, _s, _pl = direct_transport_rounds(
        state, chain, 2, CON, meta.num_topics, MASKS, 16)
    viol_repl_before = _replica_band_violation(st)
    st2, moves, _sw, _pl = direct_transport_rounds(
        st, chain, 3, CON, meta.num_topics, MASKS, 16)
    assert int(moves) > 0
    assert _sibling_clean(st2)
    # prior replica band untouched (guards held jointly across the batch)
    assert _replica_band_violation(st2) <= viol_repl_before + 1e-6
    tb = np.asarray(topic_broker_replica_counts(st2, meta.num_topics))
    d2 = compute_derived(st2)
    alive = np.asarray(d2.alive)
    avg = (tb * alive[None, :]).sum(1) / max(int(alive.sum()), 1)
    up = np.ceil(avg * CON.topic_replica_balance_threshold)
    lo = np.floor(avg / CON.topic_replica_balance_threshold)
    viol = ((np.maximum(tb - up[:, None], 0)
             + np.maximum(lo[:, None] - tb, 0)) * alive[None, :]).sum()
    before = _topic_band_violation(st, meta.num_topics)
    assert viol < before, (viol, before)


def _replica_band_violation(state) -> float:
    derived = compute_derived(state)
    lo, up = count_limits(derived.avg_replicas,
                          CON.replica_balance_threshold)
    cnt = np.asarray(broker_replica_counts(state))
    alive = np.asarray(derived.alive)
    return float(np.sum((np.maximum(cnt - float(up), 0)
                         + np.maximum(float(lo) - cnt, 0)) * alive))


def _topic_band_violation(state, num_topics) -> float:
    tb = np.asarray(topic_broker_replica_counts(state, num_topics))
    derived = compute_derived(state)
    alive = np.asarray(derived.alive)
    avg = (tb * alive[None, :]).sum(1) / max(int(alive.sum()), 1)
    up = np.ceil(avg * CON.topic_replica_balance_threshold)
    lo = np.floor(avg / CON.topic_replica_balance_threshold)
    return float(((np.maximum(tb - up[:, None], 0)
                   + np.maximum(lo[:, None] - tb, 0))
                  * alive[None, :]).sum())


def test_direct_respects_rack_awareness():
    """With a rack goal stacked prior, the transport never creates a
    rack duplicate: starting from a rack-clean state, duplicates stay at
    zero through the replica and topic transports."""
    state, meta = _cluster()
    rack_chain = (RackAwareGoal(), ReplicaCapacityGoal(),
                  ReplicaDistributionGoal(), TopicReplicaDistributionGoal())
    # Clean racks first with the greedy rack goal.
    st, _ = optimize_goal_in_chain(state, rack_chain, 0, CON, CFG,
                                   meta.num_topics, MASKS)
    assert _rack_duplicates(st) == 0
    st2, _m, _s, _pl = direct_transport_rounds(
        st, rack_chain, 2, CON, meta.num_topics, MASKS, 16)
    st3, _m2, _s2, _pl2 = direct_transport_rounds(
        st2, rack_chain, 3, CON, meta.num_topics, MASKS, 16)
    assert _rack_duplicates(st3) == 0
    assert _sibling_clean(st3)


def test_direct_respects_exclusion_masks():
    """Excluded-for-replica-move brokers receive NOTHING from the
    transport, and partitions of excluded topics never move."""
    state, meta = _cluster()
    chain = (RackAwareGoal(), ReplicaCapacityGoal(),
             ReplicaDistributionGoal())
    excluded = jnp.zeros(state.num_brokers, dtype=bool).at[7].set(True) \
        .at[11].set(True)
    topic_mask = jnp.asarray(
        np.array([t == 0 for t in np.asarray(state.topic)], dtype=bool))
    masks = ExclusionMasks(excluded_topics=topic_mask,
                           excluded_replica_move_brokers=excluded)
    before = np.asarray(broker_replica_counts(state))
    a_before = np.asarray(state.assignment)
    st, _m, _s, _pl = direct_transport_rounds(
        state, chain, 2, CON, meta.num_topics, masks, 16)
    after = np.asarray(broker_replica_counts(st))
    assert after[7] <= before[7] and after[11] <= before[11]
    # excluded-topic rows byte-identical
    t0_rows = np.asarray(state.topic) == 0
    np.testing.assert_array_equal(np.asarray(st.assignment)[t0_rows],
                                  a_before[t0_rows])


def test_leadership_mode_transfers_leadership_only():
    """The leader-count goal's transport re-elects sibling replicas:
    leader counts move toward the band while the ASSIGNMENT (replica
    placement) stays byte-identical — and a PRIOR resource goal's band
    is respected on both sides (leadership shifts leader−follower load
    off the source and onto the destination)."""
    state, meta = _cluster(seed=42)
    chain = (RackAwareGoal(), ReplicaCapacityGoal(),
             NetworkOutboundUsageDistributionGoal(),
             LeaderReplicaDistributionGoal())
    before = _leader_band_violation(state)
    nwout_before = _resource_band_violation(state, 2)
    st, moves, _sw, _pl = direct_transport_rounds(
        state, chain, 3, CON, meta.num_topics, MASKS, 16)
    # prior NwOut band not worsened by the joint leadership plan
    assert _resource_band_violation(st, 2) <= nwout_before + 1e-3
    np.testing.assert_array_equal(np.asarray(st.assignment),
                                  np.asarray(state.assignment))
    assert int(moves) > 0
    assert _leader_band_violation(st) < before
    # every leader slot still points at an existing replica
    exists = np.asarray(replica_exists(st))
    ls = np.asarray(st.leader_slot)
    pm = np.asarray(st.partition_mask)
    for pi in range(ls.shape[0]):
        if pm[pi] and ls[pi] >= 0:
            assert exists[pi, ls[pi]]


def _resource_band_violation(state, r: int) -> float:
    from cruise_control_tpu.analyzer.derived import resource_limits
    from cruise_control_tpu.common.resources import Resource
    derived = compute_derived(state)
    lo, up, _c = resource_limits(state, derived, CON, Resource(r))
    load = np.asarray(derived.broker_load[:, r])
    alive = np.asarray(derived.alive)
    return float(np.sum((np.maximum(load - np.asarray(up), 0)
                         + np.maximum(np.asarray(lo) - load, 0)) * alive))


def _leader_band_violation(state) -> float:
    derived = compute_derived(state)
    lo, up = count_limits(derived.avg_leaders,
                          CON.leader_replica_balance_threshold)
    cnt = np.asarray(broker_leader_counts(state))
    alive = np.asarray(derived.alive)
    return float(np.sum((np.maximum(cnt - float(up), 0)
                         + np.maximum(float(lo) - cnt, 0)) * alive))


def test_direct_full_chain_composes_with_greedy_polish():
    """Direct pre-pass + greedy polish through the whole chain: hard
    goals all succeed, count-goal work moves into kind="direct"
    dispatches, and the succeeded set matches the greedy-only run on
    this fixture."""
    state, meta = _cluster()
    g_st, g_infos = _run_chain(state, meta, GREEDY)
    stats = DispatchStats()
    st = state
    d_infos = []
    for i in range(len(CHAIN)):
        st, info = optimize_goal_in_chain(
            st, CHAIN, i, CON, CFG, meta.num_topics, MASKS,
            dispatch_rounds=8, megastep=DIRECT, stats=stats,
            donate_input=bool(d_infos) and any(
                x["rounds"] > 0 or x.get("direct_sweeps", 0) > 0
                for x in d_infos))
        d_infos.append(info)
    # Hard goals and the replica/leader count goals must land exactly
    # where the greedy run does. TopicReplica alone gets a one-count
    # tolerance: the upstream ReplicaDistribution transport lands this
    # 96-partition fixture in a different (equally valid) basin, and
    # from that basin GREEDY TR strands the same single count-unit the
    # direct run does — the divergence is basin quantization on a tiny
    # fixture, not a transport defect. The regime-scale quality pins
    # (violated set equality, balancedness) live in
    # test_direct_topic_plane_solves_sparse_regime and the bench canary.
    for i in range(len(CHAIN)):
        if i == TR_IDX:
            assert abs(d_infos[i]["residual_violation"]
                       - g_infos[i]["residual_violation"]) <= 1.0
        else:
            assert d_infos[i]["succeeded"] == g_infos[i]["succeeded"], \
                CHAIN[i].name
    count_infos = [d_infos[REPL_IDX], d_infos[TR_IDX], d_infos[LEAD_IDX]]
    assert all("direct_sweeps" in i for i in count_infos)
    assert sum(i.get("direct_moves", 0) for i in count_infos) > 0
    # TopicReplica runs the transport too now (round 21): the
    # sparse-aware fractional plan retired the density gate, so ALL
    # direct-eligible count goals with entry violations get the
    # pre-pass.
    assert stats.by_kind.get("direct", 0) >= 3
    assert stats.as_dict()["direct_dispatches"] == stats.by_kind["direct"]
    assert _sibling_clean(st)


def test_direct_below_gate_byte_parity(tmp_path):
    """With the kernel ENABLED but the cluster below the wide-regime
    gate, the optimizer's result is byte-identical to the disabled
    config — at two padded bucket shapes (the disabled-path pin).
    This is the surviving gate after the density gate's retirement:
    below ``solver.wide.batch.min.brokers`` the greedy byte-parity pins
    must keep holding, sparse plan or not."""
    from cruise_control_tpu.analyzer.optimizer import GoalOptimizer
    from cruise_control_tpu.config.cruise_control_config import (
        CruiseControlConfig,
    )
    for bucket in (32, 128):
        state, meta = _cluster(partition_bucket=bucket)
        outs = []
        for enabled in (False, True):
            opt = GoalOptimizer(CruiseControlConfig({
                "solver.direct.assignment.enabled": enabled}))
            # 12 brokers < solver.wide.batch.min.brokers (512): the
            # resolved megastep must keep direct OFF.
            assert opt._megastep_config(
                state.num_brokers).direct_assignment is False or not enabled
            st, res = opt.optimizations(state, meta)
            outs.append((np.asarray(st.assignment).copy(),
                         np.asarray(st.leader_slot).copy(),
                         [dataclasses.asdict(g) for g in res.goal_results]))
        np.testing.assert_array_equal(outs[0][0], outs[1][0])
        np.testing.assert_array_equal(outs[0][1], outs[1][1])
        for a, b in zip(outs[0][2], outs[1][2]):
            a.pop("duration_s"), b.pop("duration_s")
            assert a == b


def test_megabatch_direct_pads_frozen_and_parity():
    """A direct solve on a partially-filled batch: inert pad slots stay
    byte-frozen, the occupied slot matches the solo solve, and a
    different occupancy reuses the SAME compiled program (occupancy is
    traced)."""
    state, meta = _cluster()
    chain = (RackAwareGoal(), ReplicaCapacityGoal(),
             ReplicaDistributionGoal())
    inert = inert_state_like(state)
    batched = stack_states([state, inert, inert, inert])
    active0 = jnp.asarray([True, False, False, False])
    cache0 = megabatch_direct_rounds._cache_size()
    out, mv, sw, _act = megabatch_direct_rounds(
        batched, active0, chain, 2, CON, meta.num_topics, MASKS, 8)
    solo, smv, _ssw, _spl = direct_transport_rounds(
        state, chain, 2, CON, meta.num_topics, MASKS, 8)
    np.testing.assert_array_equal(
        np.asarray(unstack_state(out, 0).assignment),
        np.asarray(solo.assignment))
    assert int(np.asarray(mv)[0]) == int(smv)
    for b in (1, 2, 3):
        np.testing.assert_array_equal(
            np.asarray(unstack_state(out, b).assignment),
            np.asarray(inert.assignment))
        assert int(np.asarray(mv)[b]) == 0
        assert int(np.asarray(sw)[b]) == 0
    assert megabatch_direct_rounds._cache_size() - cache0 == 1
    # Second occupancy: same program (no compiled-program-per-occupancy
    # regression — the jit cache counter pin).
    state2, _ = _cluster(seed=7)
    batched2 = stack_states([state, state2, inert, inert])
    megabatch_direct_rounds(batched2, jnp.asarray([True, True, False, False]),
                            chain, 2, CON, meta.num_topics, MASKS, 8)
    assert megabatch_direct_rounds._cache_size() - cache0 == 1


def test_direct_dispatch_telemetry_out_of_density_histogram():
    """kind="direct" dispatches: density 0.0, excluded from the
    acceptance-density histogram, counted by the recorder, and the goal
    summary labels the solve mode."""
    from cruise_control_tpu.utils.flight_recorder import FlightRecorder
    rec = FlightRecorder(max_passes=4, ring_rounds=0)
    with rec.pass_scope(seq=1, shape=(96, 12)) as p:
        g = p.goal("TopicReplicaDistributionGoal")
        g.grid(32, 8, 32)
        g.entry(violation=40.0)
        g.dispatch("direct", 8, 3, 37, elapsed_s=0.1)
        g.dispatch("move", 16, 2, 3, elapsed_s=0.1)
        g.exit(violation=0.0)
    d = rec.passes()[0]["goals"][0]
    assert d["solveMode"] == "direct+greedy"
    kinds = {x["kind"]: x for x in d["dispatches"]}
    assert kinds["direct"]["acceptanceDensity"] == 0.0
    assert kinds["move"]["acceptanceDensity"] > 0.0
    # density aggregate counts MOVE dispatches only
    assert d["acceptanceDensity"] == pytest.approx(3 / 2 / 32, rel=1e-6)
    # summarize_passes surfaces the direct tally only when present
    from cruise_control_tpu.utils.flight_recorder import summarize_passes
    summary = summarize_passes(rec.passes())
    assert summary["directDispatches"] == 1
    assert summary["directMoves"] == 37
    rec2 = FlightRecorder(max_passes=4, ring_rounds=0)
    with rec2.pass_scope(seq=1, shape=(96, 12)) as p:
        g = p.goal("x")
        g.grid(32, 8, 32)
        g.dispatch("move", 16, 2, 3)
    s2 = summarize_passes(rec2.passes())
    assert "directDispatches" not in s2
    assert s2["passes"] == 1


def test_run_direct_pass_records_stats_and_flight():
    state, meta = _cluster()
    chain = (RackAwareGoal(), ReplicaCapacityGoal(),
             ReplicaDistributionGoal())
    stats = DispatchStats()
    from cruise_control_tpu.utils.flight_recorder import FlightRecorder
    rec = FlightRecorder(max_passes=4, ring_rounds=0)
    with rec.pass_scope(seq=1, shape=(96, 12)) as p:
        g = p.goal("ReplicaDistributionGoal")
        st, moves, sweeps, donated, _stranded = run_direct_pass(
            state, chain, 2, CON, meta.num_topics, MASKS, DIRECT, 8,
            stats=stats, flight=g)
    assert moves > 0 and sweeps > 0
    assert donated is False            # CPU backend: donation gated off
    assert stats.by_kind == {"direct": 1}
    d = rec.passes()[0]["goals"][0]
    assert d["solveMode"] == "direct"
    assert d["dispatches"][0]["kind"] == "direct"
    assert d["dispatches"][0]["rounds"] == sweeps
    assert d["dispatches"][0]["applied"] == moves


# ---------------------------------------------------------------------------
# Density-aware per-goal path choice (ROADMAP 2d, round 23)

def test_replica_density_is_replicas_per_transport_cell():
    from cruise_control_tpu.analyzer.optimizer import replica_density
    state, meta = _cluster()
    expect = (int(state.num_partitions) * int(state.assignment.shape[-1])
              / (meta.num_topics * int(state.num_brokers)))
    assert replica_density(state, meta.num_topics) == pytest.approx(expect)


def test_direct_goal_choice_threshold_semantics():
    from cruise_control_tpu.analyzer.optimizer import (
        _SPARSE_DIRECT_GOALS, direct_goal_choice,
    )
    # Dense regime or disabled choice: every eligible goal stays direct.
    assert direct_goal_choice(4.0, 2.0) is None
    assert direct_goal_choice(2.0, 2.0) is None       # at-threshold = dense
    assert direct_goal_choice(0.5, 0.0) is None       # threshold off
    assert direct_goal_choice(0.5, -1.0) is None
    # Sparse: only the goals measured faster on the direct arm keep it.
    assert direct_goal_choice(1.5, 2.0) == _SPARSE_DIRECT_GOALS
    assert "TopicReplicaDistributionGoal" in _SPARSE_DIRECT_GOALS


def test_direct_path_chosen_gates_per_goal():
    from cruise_control_tpu.analyzer.chain import direct_path_chosen
    all_direct = MegastepConfig(direct_assignment=True)
    assert direct_path_chosen(all_direct, "ReplicaDistributionGoal")
    assert direct_path_chosen(all_direct, "TopicReplicaDistributionGoal")
    sparse = MegastepConfig(direct_assignment=True,
                            direct_goals=("TopicReplicaDistributionGoal",))
    assert direct_path_chosen(sparse, "TopicReplicaDistributionGoal")
    assert not direct_path_chosen(sparse, "ReplicaDistributionGoal")
    assert not direct_path_chosen(sparse, "LeaderReplicaDistributionGoal")


def test_optimizer_wires_density_into_megastep_config():
    from cruise_control_tpu.analyzer.optimizer import (
        _SPARSE_DIRECT_GOALS, GoalOptimizer,
    )
    from cruise_control_tpu.config.cruise_control_config import (
        CruiseControlConfig,
    )
    opt = GoalOptimizer(CruiseControlConfig({
        "solver.direct.assignment.enabled": True,
        "solver.wide.batch.min.brokers": 8}))
    dense = opt._megastep_config(12, density=3.0)
    assert dense.direct_assignment and dense.direct_goals is None
    sparse = opt._megastep_config(12, density=1.5)   # default threshold 2.0
    assert sparse.direct_assignment
    assert sparse.direct_goals == _SPARSE_DIRECT_GOALS
    # density=None (non-model callers) skips the choice entirely.
    assert opt._megastep_config(12).direct_goals is None
    # Threshold 0 disables the choice even at sparse geometry.
    off = GoalOptimizer(CruiseControlConfig({
        "solver.direct.assignment.enabled": True,
        "solver.wide.batch.min.brokers": 8,
        "solver.direct.density.sparse.threshold": 0.0}))
    assert off._megastep_config(12, density=0.5).direct_goals is None
