"""Incremental device-resident model pipeline (model/refresh.py):
topology-cache transitions, byte-identical incremental-vs-cold pins,
donation-path reuse, bucket hysteresis, and the LoadMonitor/fleet wiring."""

import numpy as np
import pytest

from cruise_control_tpu.common.broker_state import BrokerState
from cruise_control_tpu.common.resources import NUM_RESOURCES, Resource
from cruise_control_tpu.executor.admin import InMemoryAdminBackend, PartitionState
from cruise_control_tpu.model.builder import BrokerSpec, graduated_bucket
from cruise_control_tpu.model.refresh import (
    IncrementalModelPipeline, TOPOLOGY_FIELDS,
)

_CAP = {Resource.CPU: 100.0, Resource.NW_IN: 1000.0,
        Resource.NW_OUT: 1000.0, Resource.DISK: 10000.0}


def _brokers(n):
    return [BrokerSpec(i, rack=f"r{i % 3}", capacity=_CAP,
                       state=BrokerState.ALIVE, host=f"h{i // 2}")
            for i in range(n)]


def _partitions(num_brokers, num_partitions, rf=3, topics=4):
    out = {}
    for i in range(num_partitions):
        topic, part = f"t{i % topics}", i // topics
        reps = tuple((i * 7 + k) % num_brokers for k in range(rf))
        out[(topic, part)] = PartitionState(topic, part, reps, reps[0],
                                            isr=reps)
    return out


def _filler(seed):
    def fill(cache):
        rng = np.random.default_rng(seed)
        n = len(cache.part_names)
        cache.ll_buf[:n] = rng.random((n, NUM_RESOURCES)).astype(np.float32)
        cache.fl_buf[:n] = cache.ll_buf[:n] * np.float32(0.5)
        cache.fl_buf[:n, int(Resource.NW_OUT)] = 0.0
    return fill


def _assert_states_identical(a, b):
    for f in TOPOLOGY_FIELDS + ("leader_load", "follower_load", "leader_slot"):
        xa, xb = np.asarray(getattr(a, f)), np.asarray(getattr(b, f))
        assert xa.dtype == xb.dtype, f
        assert np.array_equal(xa, xb), f


@pytest.mark.parametrize("num_brokers,num_partitions", [(5, 64), (12, 300)])
def test_incremental_refresh_byte_identical_to_cold_rebuild(
        num_brokers, num_partitions):
    """The correctness bar: a load-only refresh through the warm cache is
    byte-identical to a cold rebuild with the same inputs — at two cluster
    sizes, across a load change AND a topology change."""
    parts = _partitions(num_brokers, num_partitions)
    warm = IncrementalModelPipeline(partition_bucket=32, broker_bucket=4)
    warm.assemble(_brokers(num_brokers), parts, _filler(0), topology_token=0)
    # Load-only change: warm pipeline takes the hit path.
    s_inc, m_inc = warm.assemble(_brokers(num_brokers), parts, _filler(1),
                                 topology_token=0)
    assert warm.topology_hits == 1 and warm.topology_misses == 1
    cold = IncrementalModelPipeline(partition_bucket=32, broker_bucket=4)
    s_cold, m_cold = cold.assemble(_brokers(num_brokers), parts, _filler(1),
                                   topology_token=0)
    _assert_states_identical(s_inc, s_cold)
    assert m_inc == m_cold

    # Topology change (replica set moved): both rebuild, still identical.
    (tp, st) = next(iter(sorted(parts.items())))
    new_reps = tuple((b + 1) % num_brokers for b in st.replicas)
    parts[tp] = PartitionState(st.topic, st.partition, new_reps, new_reps[0],
                               isr=new_reps)
    s_inc2, _ = warm.assemble(_brokers(num_brokers), parts, _filler(2),
                              topology_token=1)
    assert warm.topology_misses == 2
    s_cold2, _ = cold.assemble(_brokers(num_brokers), parts, _filler(2),
                               topology_token=1)
    _assert_states_identical(s_inc2, s_cold2)


def test_topology_cache_dirty_and_clean_transitions():
    parts = _partitions(6, 48)
    pipe = IncrementalModelPipeline()
    pipe.assemble(_brokers(6), parts, _filler(0), topology_token=7)
    assert (pipe.topology_misses, pipe.topology_hits) == (1, 0)
    # Clean: same token → hit; repeated hits stay hits.
    pipe.assemble(_brokers(6), parts, _filler(1), topology_token=7)
    pipe.assemble(_brokers(6), parts, _filler(2), topology_token=7)
    assert (pipe.topology_misses, pipe.topology_hits) == (1, 2)
    # Dirty: token bump → miss even with identical content.
    pipe.assemble(_brokers(6), parts, _filler(3), topology_token=8)
    assert (pipe.topology_misses, pipe.topology_hits) == (2, 2)
    # Dirty: broker-table change (capacity) invalidates under a clean token.
    brokers = _brokers(6)
    brokers[0] = BrokerSpec(0, rack="r0", capacity={Resource.CPU: 7.0},
                            state=BrokerState.ALIVE, host="h0")
    pipe.assemble(brokers, parts, _filler(4), topology_token=8)
    assert (pipe.topology_misses, pipe.topology_hits) == (3, 2)


def test_fingerprint_fallback_detects_replica_and_leader_changes():
    """Without a metadata-generation token the pipeline fingerprints the
    replica structure; leader-only elections must stay on the hit path
    (leadership is re-derived every refresh from the live states)."""
    parts = _partitions(5, 40)
    pipe = IncrementalModelPipeline()
    pipe.assemble(_brokers(5), parts, _filler(0))
    s1, _ = pipe.assemble(_brokers(5), parts, _filler(1))
    assert pipe.topology_hits == 1

    # Leader-only change: still a hit, and the new leader slot shows up.
    tp = sorted(parts)[0]
    st = parts[tp]
    parts[tp] = PartitionState(st.topic, st.partition, st.replicas,
                               st.replicas[1], isr=st.replicas)
    s2, _ = pipe.assemble(_brokers(5), parts, _filler(1))
    assert pipe.topology_hits == 2
    row = sorted(parts).index(tp)
    assert int(np.asarray(s2.leader_slot)[row]) == 1
    assert int(np.asarray(s1.leader_slot)[row]) == 0

    # Replica-set change: fingerprint differs → rebuild.
    parts[tp] = PartitionState(st.topic, st.partition,
                               tuple((b + 1) % 5 for b in st.replicas),
                               (st.replicas[0] + 1) % 5, isr=())
    pipe.assemble(_brokers(5), parts, _filler(1))
    assert pipe.topology_misses == 2


def test_refresh_reuses_topology_device_buffers_and_donation_path():
    """Hit-path reuse: topology tensors are the SAME device buffers across
    refreshes (zero re-transfer), and the donate=True shipper produces
    identical values. A still-referenced previous state is never donated
    (the sole-owner guard), so its arrays stay readable."""
    parts = _partitions(4, 32)
    pipe = IncrementalModelPipeline(donate=True)
    s0, _ = pipe.assemble(_brokers(4), parts, _filler(0), topology_token=0)
    s1, _ = pipe.assemble(_brokers(4), parts, _filler(1), topology_token=0)
    for f in TOPOLOGY_FIELDS:
        assert getattr(s0, f) is getattr(s1, f), f
    # s0 is still alive here: the sole-owner guard must have refused to
    # donate its load buffers — they remain readable and correct.
    ref = IncrementalModelPipeline().assemble(
        _brokers(4), parts, _filler(0), topology_token=0)[0]
    assert np.array_equal(np.asarray(s0.leader_load),
                          np.asarray(ref.leader_load))
    # Drop every external reference and refresh twice: the donation path
    # (or its CPU no-op) must keep producing byte-identical loads.
    del s0, ref
    s2, _ = pipe.assemble(_brokers(4), parts, _filler(2), topology_token=0)
    del s1
    s3, _ = pipe.assemble(_brokers(4), parts, _filler(3), topology_token=0)
    want = IncrementalModelPipeline().assemble(
        _brokers(4), parts, _filler(3), topology_token=0)[0]
    assert np.array_equal(np.asarray(s3.leader_load),
                          np.asarray(want.leader_load))
    del s2


def test_graduated_bucket_hysteresis_absorbs_boundary_flap():
    # Bucket 64 is freshly selected at n >= 512; without hysteresis a
    # cluster oscillating 511<->512 flips 32<->64 every cycle.
    assert graduated_bucket(512, 1024) == 64
    assert graduated_bucket(511, 1024) == 32
    # With the previous bucket pinned, ±1 hovering keeps the shape...
    assert graduated_bucket(511, 1024, prev=64) == 64
    assert graduated_bucket(512, 1024, prev=32) == 32
    # ...but a real move past the hysteresis margin switches.
    assert graduated_bucket(int(512 * 0.8), 1024, prev=64) == 32
    assert graduated_bucket(int(1024 * 1.2), 1024, prev=32) == 128
    # prev from a different config (not reachable) is ignored.
    assert graduated_bucket(512, 1024, prev=4096) == 64


def test_load_monitor_uses_cache_and_metadata_generation():
    """End-to-end monitor wiring: repeated cluster_model calls with
    unchanged metadata hit the topology cache and agree exactly with the
    first build; a broker death (metadata generation bump) rebuilds and
    marks the broker DEAD."""
    from cruise_control_tpu.config.cruise_control_config import (
        CruiseControlConfig,
    )
    from cruise_control_tpu.monitor import (
        LoadMonitor, ModelCompletenessRequirements,
    )
    from cruise_control_tpu.monitor.sampling import SyntheticSampler

    parts = _partitions(3, 12, rf=2)
    backend = InMemoryAdminBackend(parts.values())
    cfg = CruiseControlConfig({"partition.metrics.window.ms": 1000,
                               "num.partition.metrics.windows": 2,
                               "min.valid.partition.ratio": 0.0})
    monitor = LoadMonitor(cfg, backend, samplers=[SyntheticSampler()])
    monitor.task_runner.run_sampling_once(end_ms=1000)
    monitor.task_runner.run_sampling_once(end_ms=2000)
    req = ModelCompletenessRequirements(1, 0.0)
    s1, m1 = monitor.cluster_model(req)
    assert monitor.pipeline.topology_misses == 1
    s2, m2 = monitor.cluster_model(req)
    assert monitor.pipeline.topology_hits == 1
    _assert_states_identical(s1, s2)
    assert m1 == m2

    backend.kill_broker(1)
    s3, m3 = monitor.cluster_model(req)
    assert monitor.pipeline.topology_misses == 2
    dead = np.asarray(s3.broker_state) == int(BrokerState.DEAD)
    assert dead[m3.broker_ids.index(1)]

    # New samples only (load change, topology unchanged): hit again, and
    # the refreshed state reflects the new aggregation generation.
    monitor.task_runner.run_sampling_once(end_ms=3000)
    monitor.cluster_model(req)
    assert monitor.pipeline.topology_hits == 2


def test_prefetch_model_overlaps_and_is_consumed_once():
    from cruise_control_tpu.config.cruise_control_config import (
        CruiseControlConfig,
    )
    from cruise_control_tpu.monitor import LoadMonitor
    from cruise_control_tpu.monitor.sampling import SyntheticSampler

    parts = _partitions(3, 9, rf=2)
    backend = InMemoryAdminBackend(parts.values())
    cfg = CruiseControlConfig({"partition.metrics.window.ms": 1000,
                               "num.partition.metrics.windows": 2,
                               "min.valid.partition.ratio": 0.0})
    monitor = LoadMonitor(cfg, backend, samplers=[SyntheticSampler()])
    monitor.task_runner.run_sampling_once(end_ms=1000)
    monitor.task_runner.run_sampling_once(end_ms=2000)
    assert monitor.prefetch_model() is True
    monitor._prefetch_thread.join(timeout=30)
    assert monitor._prefetched is not None
    pre = monitor._prefetched[2]
    # The next default-argument call consumes the prebuilt model...
    got = monitor.cluster_model()
    assert got is pre
    # ...exactly once.
    again = monitor.cluster_model()
    assert again is not pre
    _assert_states_identical(got[0], again[0])

    # A stale prefetch (aggregation generation moved on) is discarded.
    assert monitor.prefetch_model() is True
    monitor._prefetch_thread.join(timeout=30)
    monitor.task_runner.run_sampling_once(end_ms=3000)
    stale = monitor._prefetched[2]
    fresh = monitor.cluster_model()
    assert fresh is not stale

    # A topology-stale prefetch (metadata generation bumped, NO new
    # samples) is discarded too: the dead broker must show up.
    assert monitor.prefetch_model() is True
    monitor._prefetch_thread.join(timeout=30)
    stale2 = monitor._prefetched[2]
    backend.kill_broker(2)
    served = monitor.cluster_model()
    assert served is not stale2
    dead = np.asarray(served[0].broker_state) == int(BrokerState.DEAD)
    assert dead[served[1].broker_ids.index(2)]


def test_fleet_pacer_kicks_model_prefetch():
    """The precompute pacer's overlap hook: pace_once() starts a model
    prefetch for the cluster it enqueues."""
    from cruise_control_tpu.fleet.scheduler import FleetScheduler

    class _Monitor:
        def __init__(self):
            self.prefetches = 0

        def prefetch_model(self):
            self.prefetches += 1
            return True

    class _CC:
        def __init__(self):
            self.load_monitor = _Monitor()
            self.calls = 0

        def proposals(self):
            self.calls += 1
            return "ok"

    class _Entry:
        def __init__(self, cid, cc):
            self.cluster_id, self.cc = cid, cc
            self.paused = False
            self.last_precompute = 0.0
            from cruise_control_tpu.config.cruise_control_config import (
                CruiseControlConfig,
            )
            self.config = CruiseControlConfig(
                {"fleet.precompute.cadence.ms": 1})

    class _Registry:
        def __init__(self, entries):
            self._entries = entries

        def entries(self):
            return self._entries

    cc = _CC()
    sched = FleetScheduler(clock=lambda: 100.0)
    sched.bind(_Registry([_Entry("alpha", cc)]))
    assert sched.pace_once() == 1
    assert cc.load_monitor.prefetches == 1
    assert sched.run_pending() == 1
    assert cc.calls == 1
