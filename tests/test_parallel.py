"""Sharded (multi-device mesh) search vs single-device search.

Runs on the 8-device virtual CPU platform from conftest.py. Mirrors the
reference's approach of testing multi-node behavior in-process (SURVEY.md §4:
embedded brokers + model-level simulation) — here the mesh IS real SPMD, just
on virtual devices.
"""

import jax
import numpy as np
import pytest

from cruise_control_tpu.analyzer.constraint import BalancingConstraint
from cruise_control_tpu.analyzer.derived import compute_derived
from cruise_control_tpu.analyzer.goals import (
    RackAwareGoal, ReplicaDistributionGoal, NetworkOutboundUsageDistributionGoal,
    TopicReplicaDistributionGoal,
)
from cruise_control_tpu.analyzer.search import ExclusionMasks, SearchConfig, optimize_goal
from cruise_control_tpu.model.fixtures import random_cluster
from cruise_control_tpu.model.tensors import broker_load, broker_replica_counts
from cruise_control_tpu.parallel import (
    make_mesh, optimize_goal_sharded, shard_cluster,
)

CONSTRAINT = BalancingConstraint()
CFG = SearchConfig(num_sources=32, num_dests=8, moves_per_round=8, max_rounds=40)


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) >= 8, "conftest must provide 8 virtual devices"
    return make_mesh(8)


@pytest.fixture(scope="module")
def cluster():
    # 16 partitions/shard × 8 shards; skewed so there is work to do.
    return random_cluster(num_brokers=12, num_topics=6, num_partitions=128,
                          rf=2, num_racks=4, seed=7, skew_to_first=2.0,
                          partition_bucket=8)


def test_shard_cluster_roundtrip(mesh, cluster):
    state, meta = cluster
    sharded = shard_cluster(state, mesh)
    np.testing.assert_array_equal(np.asarray(sharded.assignment),
                                  np.asarray(state.assignment))
    assert sharded.assignment.sharding.spec[0] == "p"


def test_sharded_replica_distribution_balances(mesh, cluster):
    state, meta = cluster
    goal = ReplicaDistributionGoal()
    sharded = shard_cluster(state, mesh)
    out, info = optimize_goal_sharded(sharded, goal, (), CONSTRAINT, CFG,
                                      meta.num_topics, mesh)
    assert info["moves_applied"] > 0
    # Single-device reference run reaches the same satisfied end state.
    out_ref, info_ref = optimize_goal(state, goal, (), CONSTRAINT, CFG,
                                      meta.num_topics)
    assert info["succeeded"] and info_ref["succeeded"]
    counts = np.asarray(broker_replica_counts(jax.device_get(out)))
    counts_ref = np.asarray(broker_replica_counts(out_ref))
    assert counts.max() - counts.min() <= counts_ref.max() - counts_ref.min() + 2


def test_sharded_respects_prior_goal_acceptance(mesh, cluster):
    state, meta = cluster
    rack = RackAwareGoal()
    sharded = shard_cluster(state, mesh)
    out, _ = optimize_goal_sharded(sharded, rack, (), CONSTRAINT, CFG,
                                   meta.num_topics, mesh)
    out2, _ = optimize_goal_sharded(out, ReplicaDistributionGoal(), (rack,),
                                    CONSTRAINT, CFG, meta.num_topics, mesh)
    # Rack-awareness must not regress after the second goal ran.
    full = jax.device_get(out2)
    derived = compute_derived(full)
    viol = rack.broker_violations(full, derived, CONSTRAINT, None)
    assert float(viol.sum()) <= 1e-6


def test_sharded_resource_distribution_improves_balance(mesh, cluster):
    state, meta = cluster
    goal = NetworkOutboundUsageDistributionGoal()
    before = np.asarray(broker_load(state))[:, 2]
    sharded = shard_cluster(state, mesh)
    out, info = optimize_goal_sharded(sharded, goal, (), CONSTRAINT, CFG,
                                      meta.num_topics, mesh)
    after = np.asarray(broker_load(jax.device_get(out)))[:, 2]
    assert after.std() < before.std()


def test_sharded_swap_round_matches_single_device(mesh, cluster):
    """The card-gather swap kernel must find the same swap batch as the
    single-device swap round: per-broker global top-j merged from per-shard
    top-j is exact, and selection is score-rank deterministic."""
    from cruise_control_tpu.analyzer.search import swap_round
    from cruise_control_tpu.parallel import sharded_swap_round

    state, meta = cluster
    goal = NetworkOutboundUsageDistributionGoal()
    masks = ExclusionMasks()
    ref_state, ref_n = swap_round(state, goal, (), CONSTRAINT,
                                  meta.num_topics, masks)
    sharded = shard_cluster(state, mesh)
    out, n = sharded_swap_round(sharded, goal, (), CONSTRAINT,
                                meta.num_topics, masks, mesh)
    assert int(n) == int(ref_n)
    np.testing.assert_array_equal(np.asarray(jax.device_get(out).assignment),
                                  np.asarray(ref_state.assignment))


def test_sharded_swap_respects_prior_rack_goal(mesh, cluster):
    """Swap legs are leg-accepted by prior structural goals on the owning
    device: rack-awareness must survive a swap phase under the mesh."""
    state, meta = cluster
    rack = RackAwareGoal()
    sharded = shard_cluster(state, mesh)
    out, _ = optimize_goal_sharded(sharded, rack, (), CONSTRAINT, CFG,
                                   meta.num_topics, mesh)
    goal = NetworkOutboundUsageDistributionGoal()
    out2, info = optimize_goal_sharded(out, goal, (rack,), CONSTRAINT, CFG,
                                       meta.num_topics, mesh)
    full = jax.device_get(out2)
    derived = compute_derived(full)
    viol = rack.broker_violations(full, derived, CONSTRAINT, None)
    assert float(viol.sum()) <= 1e-6


def test_sharded_driver_fuses_rounds(mesh, cluster):
    """The fused while_loop driver makes host round-trips per PHASE, not
    per round: many rounds, few round-trips."""
    state, meta = cluster
    sharded = shard_cluster(state, mesh)
    out, info = optimize_goal_sharded(sharded, ReplicaDistributionGoal(), (),
                                      CONSTRAINT, CFG, meta.num_topics, mesh)
    assert info["rounds"] > 3
    # move phase + final check only (no swap support on this goal).
    assert info["host_roundtrips"] <= 2


def test_distributed_single_process_path(mesh, cluster):
    """initialize() is a no-op single-host; global_mesh spans all devices
    and drives the sharded solver."""
    from cruise_control_tpu.parallel import distributed

    distributed.initialize()  # no coordinator configured: no-op
    info = distributed.process_info()
    assert info["process_count"] == 1
    gmesh = distributed.global_mesh()
    assert gmesh.devices.size == len(jax.devices())
    state, meta = cluster
    sharded = shard_cluster(state, gmesh)
    out, res = optimize_goal_sharded(sharded, ReplicaDistributionGoal(), (),
                                     CONSTRAINT, CFG, meta.num_topics, gmesh)
    assert res["succeeded"]


def test_sharded_full_chain_matches_single_device_outcome(mesh, cluster):
    """The fused whole-chain mesh kernel (parallel/chain_sharded.py) must
    reach the same per-goal OUTCOME as the single-device whole-chain kernel:
    identical success/violation profile and comparable balance. (Bitwise
    trajectory equality is not expected — per-device top-k candidate
    generation explores a different, equally valid move order.)"""
    from cruise_control_tpu.analyzer.chain import optimize_chain
    from cruise_control_tpu.analyzer.goals import (
        PreferredLeaderElectionGoal, ReplicaCapacityGoal,
    )
    from cruise_control_tpu.parallel import optimize_chain_sharded

    state, meta = cluster
    chain = (RackAwareGoal(), ReplicaCapacityGoal(),
             ReplicaDistributionGoal(),
             NetworkOutboundUsageDistributionGoal(),
             PreferredLeaderElectionGoal())
    cfg = SearchConfig(num_sources=32, num_dests=8, moves_per_round=8,
                       max_rounds=60)

    st_single, infos_single = optimize_chain(state, chain, CONSTRAINT, cfg,
                                             meta.num_topics)
    sharded = shard_cluster(state, mesh)
    st_mesh, infos_mesh = optimize_chain_sharded(
        sharded, chain, CONSTRAINT, cfg, meta.num_topics, mesh)

    for s, m in zip(infos_single, infos_mesh):
        assert m["goal"] == s["goal"]
        assert m["succeeded"] == s["succeeded"], (s, m)
    # Replica-count spread after the chain is comparable.
    counts_s = np.asarray(broker_replica_counts(st_single))
    counts_m = np.asarray(broker_replica_counts(jax.device_get(st_mesh)))
    spread_s = counts_s.max() - counts_s.min()
    spread_m = counts_m.max() - counts_m.min()
    assert spread_m <= spread_s + 2
    # Rack-awareness holds on the mesh result.
    full = jax.device_get(st_mesh)
    derived = compute_derived(full)
    viol = RackAwareGoal().broker_violations(full, derived, CONSTRAINT, None)
    assert float(viol.sum()) <= 1e-6


@pytest.mark.slow  # ~18 s: bounded-vs-fused trajectory sweep; the
# full-chain mesh-vs-single-device pin stays tier-1.
def test_sharded_bounded_dispatch_matches_fused(mesh, cluster):
    """The bounded per-goal sharded driver (dispatch_rounds > 0) must walk
    the IDENTICAL trajectory to the fused whole-chain mesh kernel — same
    final assignment and per-goal moves/swaps (both run the same per-device
    round bodies; only dispatch boundaries differ)."""
    from cruise_control_tpu.analyzer.goals import ReplicaCapacityGoal
    from cruise_control_tpu.parallel import optimize_chain_sharded

    state, meta = cluster
    chain = (RackAwareGoal(), ReplicaCapacityGoal(),
             ReplicaDistributionGoal(),
             NetworkOutboundUsageDistributionGoal())
    cfg = SearchConfig(num_sources=32, num_dests=8, moves_per_round=8,
                       max_rounds=60)
    sharded = shard_cluster(state, mesh)
    st_fused, infos_fused = optimize_chain_sharded(
        sharded, chain, CONSTRAINT, cfg, meta.num_topics, mesh)
    st_bounded, infos_bounded = optimize_chain_sharded(
        shard_cluster(state, mesh), chain, CONSTRAINT, cfg,
        meta.num_topics, mesh, dispatch_rounds=3)
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(st_bounded).assignment),
        np.asarray(jax.device_get(st_fused).assignment))
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(st_bounded).leader_slot),
        np.asarray(jax.device_get(st_fused).leader_slot))
    for f, b in zip(infos_fused, infos_bounded):
        assert f["goal"] == b["goal"]
        assert f["succeeded"] == b["succeeded"]
        assert f["moves_applied"] == b["moves_applied"], f["goal"]
        assert f["swaps_applied"] == b["swaps_applied"], f["goal"]


def test_goal_optimizer_uses_mesh(mesh, cluster):
    """GoalOptimizer(mesh=...) routes optimizations through the sharded
    chain kernel and reports the device count."""
    from cruise_control_tpu.analyzer.optimizer import (
        GoalOptimizer, goals_by_priority,
    )
    from cruise_control_tpu.config.cruise_control_config import (
        CruiseControlConfig,
    )

    state, meta = cluster
    cfg = CruiseControlConfig()
    opt = GoalOptimizer(cfg, mesh=mesh)
    assert opt.solver_devices() == 8
    chain = goals_by_priority(cfg, ["RackAwareGoal",
                                    "ReplicaDistributionGoal"])
    _st, result = opt.optimizations(state, meta, goals=chain)
    assert result.balancedness_after >= result.balancedness_before
    assert all(r.succeeded for r in result.goal_results
               if r.name == "RackAwareGoal")


def test_sharded_topic_replica_aux_psum(mesh, cluster):
    """TopicReplicaDistributionGoal's [T, B] aux is additive across shards —
    the production sharded chain kernel (psum'd aux + joint cumulative
    selection) must reach the single-device outcome. The LEGACY per-goal
    sharded driver is excluded: its narrower per-device candidate slice can
    strand a last violation the fused paths fix (pre-existing; the
    production path replaced it)."""
    from cruise_control_tpu.analyzer.chain import optimize_chain
    from cruise_control_tpu.parallel import optimize_chain_sharded

    state, meta = cluster
    goal = TopicReplicaDistributionGoal()
    chain = (goal,)
    cfg = SearchConfig(num_sources=32, num_dests=8, moves_per_round=8,
                       max_rounds=120)
    sharded = shard_cluster(state, mesh)
    _out, infos = optimize_chain_sharded(sharded, chain, CONSTRAINT, cfg,
                                         meta.num_topics, mesh)
    _out_ref, infos_ref = optimize_chain(state, chain, CONSTRAINT, cfg,
                                         meta.num_topics)
    # The two paths walk different (both valid) trajectories; on a tiny
    # fixture a soft goal may strand a residual count-unit in one local
    # optimum and not the other. Require comparable quality, not identical
    # outcomes.
    assert infos[0]["moves_applied"] > 0
    assert infos[0]["residual_violation"] <= \
        infos_ref[0]["residual_violation"] + 2


def _direct_chain():
    from cruise_control_tpu.analyzer.goals import ReplicaCapacityGoal

    return (RackAwareGoal(), ReplicaCapacityGoal(),
            ReplicaDistributionGoal(), TopicReplicaDistributionGoal())


def test_sharded_direct_prepass_mesh1_matches_single_device_bytes(cluster):
    """The mesh direct pre-pass at rank_stride=1 (a 1-device mesh) must
    be BYTE-identical to the single-device bounded trajectory with the
    same megastep — the stride layout at stride 1 is algebraically the
    plain kernel, so any divergence is a mesh-path bug, not a different
    valid basin. Assignment AND leader_slot are pinned."""
    from cruise_control_tpu.analyzer.chain import (
        DispatchStats, MegastepConfig, optimize_goal_in_chain,
    )
    from cruise_control_tpu.parallel import optimize_chain_sharded

    state, meta = cluster
    chain = _direct_chain()
    cfg = SearchConfig(num_sources=32, num_dests=8, moves_per_round=8,
                       max_rounds=60)
    ms = MegastepConfig(direct_assignment=True, direct_max_sweeps=16)

    st1 = state
    for i in range(len(chain)):
        st1, _ = optimize_goal_in_chain(st1, chain, i, CONSTRAINT, cfg,
                                        meta.num_topics, dispatch_rounds=3,
                                        megastep=ms)
    mesh1 = make_mesh(1)
    stats = DispatchStats()
    stm, _ = optimize_chain_sharded(
        shard_cluster(state, mesh1), chain, CONSTRAINT, cfg,
        meta.num_topics, mesh1, dispatch_rounds=3, megastep=ms,
        stats=stats)
    assert stats.by_kind.get("direct", 0) >= 1
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(stm).assignment),
        np.asarray(st1.assignment))
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(stm).leader_slot),
        np.asarray(st1.leader_slot))


def test_sharded_direct_prepass_runs_deterministically_on_mesh(mesh,
                                                               cluster):
    """On the 8-way mesh the direct pre-pass actually dispatches
    (kind="direct"), the chain lands rack-clean with replica spread no
    worse than the single-device direct run +2, and the interleaved
    rank_stride layout replays byte-identically run to run (the crc32
    rounding contract has no host RNG to drift)."""
    from cruise_control_tpu.analyzer.chain import (
        DispatchStats, MegastepConfig, optimize_goal_in_chain,
    )
    from cruise_control_tpu.parallel import optimize_chain_sharded

    state, meta = cluster
    chain = _direct_chain()
    cfg = SearchConfig(num_sources=32, num_dests=8, moves_per_round=8,
                       max_rounds=60)
    ms = MegastepConfig(direct_assignment=True, direct_max_sweeps=16)

    outs = []
    for _ in range(2):
        stats = DispatchStats()
        st8, infos = optimize_chain_sharded(
            shard_cluster(state, mesh), chain, CONSTRAINT, cfg,
            meta.num_topics, mesh, dispatch_rounds=3, megastep=ms,
            stats=stats)
        assert stats.by_kind.get("direct", 0) >= 1
        outs.append((np.asarray(jax.device_get(st8).assignment).copy(),
                     np.asarray(jax.device_get(st8).leader_slot).copy()))
    np.testing.assert_array_equal(outs[0][0], outs[1][0])
    np.testing.assert_array_equal(outs[0][1], outs[1][1])

    full = jax.device_get(st8)
    derived = compute_derived(full)
    viol = RackAwareGoal().broker_violations(full, derived, CONSTRAINT, None)
    assert float(viol.sum()) <= 1e-6

    st1 = state
    for i in range(len(chain)):
        st1, _ = optimize_goal_in_chain(st1, chain, i, CONSTRAINT, cfg,
                                        meta.num_topics, dispatch_rounds=3,
                                        megastep=ms)
    c8 = np.asarray(broker_replica_counts(full))
    c1 = np.asarray(broker_replica_counts(st1))
    assert (c8.max() - c8.min()) <= (c1.max() - c1.min()) + 2
