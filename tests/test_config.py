"""Config kernel tests (reference: core ConfigDefTest / KafkaCruiseControlConfig)."""

import pytest

from cruise_control_tpu.config import (
    AbstractConfig, ConfigDef, ConfigException, ConfigType, CruiseControlConfig,
    Range, ValidString,
)
from cruise_control_tpu.config.configdef import Importance, Password


def _def():
    d = ConfigDef()
    d.define("a.int", ConfigType.INT, 7, Range.at_least(0), Importance.HIGH, "")
    d.define("b.double", ConfigType.DOUBLE, 0.5, Range.between(0, 1), Importance.LOW, "")
    d.define("c.list", ConfigType.LIST, ["x", "y"], None, Importance.LOW, "")
    d.define("d.bool", ConfigType.BOOLEAN, False, None, Importance.LOW, "")
    d.define("e.str", ConfigType.STRING, "hello", ValidString(("hello", "bye")), Importance.LOW, "")
    d.define("f.required", ConfigType.INT)
    d.define("g.pw", ConfigType.PASSWORD, None)
    return d


def test_defaults_and_coercion():
    cfg = AbstractConfig(_def(), {"f.required": "42", "a.int": "3", "d.bool": "true",
                                  "c.list": "p, q ,r"})
    assert cfg.get_int("a.int") == 3
    assert cfg.get_int("f.required") == 42
    assert cfg.get_boolean("d.bool") is True
    assert cfg.get_list("c.list") == ["p", "q", "r"]
    assert cfg.get_double("b.double") == 0.5


def test_missing_required():
    with pytest.raises(ConfigException):
        AbstractConfig(_def(), {})


def test_range_validation():
    with pytest.raises(ConfigException):
        AbstractConfig(_def(), {"f.required": 1, "a.int": -2})


def test_valid_string():
    with pytest.raises(ConfigException):
        AbstractConfig(_def(), {"f.required": 1, "e.str": "nope"})


def test_password_hidden():
    cfg = AbstractConfig(_def(), {"f.required": 1, "g.pw": "s3cret"})
    pw = cfg.get("g.pw")
    assert isinstance(pw, Password)
    assert "s3cret" not in repr(pw)
    assert pw.value == "s3cret"


def test_bad_bool_rejected():
    with pytest.raises(ConfigException):
        AbstractConfig(_def(), {"f.required": 1, "d.bool": "yes"})


def test_duplicate_key_rejected():
    d = ConfigDef()
    d.define("x", ConfigType.INT, 1)
    with pytest.raises(ConfigException):
        d.define("x", ConfigType.INT, 2)


class _FakePlugin:
    def __init__(self):
        self.configured = None

    def configure(self, config):
        self.configured = config


def test_configured_instance_loading():
    d = ConfigDef()
    d.define("plugin.class", ConfigType.CLASS, "tests.test_config._FakePlugin")
    cfg = AbstractConfig(d, {})
    inst = cfg.get_configured_instance("plugin.class")
    assert isinstance(inst, _FakePlugin)
    assert inst.configured is not None


def test_cruise_control_config_defaults():
    cfg = CruiseControlConfig()
    assert cfg.get_long("metric.sampling.interval.ms") == 120_000
    assert cfg.get_int("num.partition.metrics.windows") == 5
    assert cfg.get_int("num.broker.metrics.windows") == 20
    assert cfg.get_double("min.valid.partition.ratio") == 0.95
    assert cfg.get_double("disk.capacity.threshold") == 0.8
    assert cfg.get_int("num.concurrent.partition.movements.per.broker") == 10
    assert len(cfg.get_list("goals")) == 15
    assert set(cfg.get_list("hard.goals")) <= set(cfg.get_list("goals"))


def test_cruise_control_config_sanity_check():
    with pytest.raises(ConfigException):
        CruiseControlConfig({"hard.goals": ["not.a.goal.InGoals"]})
