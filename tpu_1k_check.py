"""Validate the bounded-dispatch solver at 1k brokers on the real TPU."""
import os
import time

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/cc_tpu_jax_cache")

import jax

print("devices:", jax.devices(), flush=True)

from cruise_control_tpu.analyzer.optimizer import GoalOptimizer, goals_by_priority
from cruise_control_tpu.config.cruise_control_config import CruiseControlConfig
from cruise_control_tpu.model.fixtures import Dist, random_cluster

t0 = time.time()
state, meta = random_cluster(
    num_brokers=1000, num_topics=100, num_partitions=100_000, rf=3,
    num_racks=8, dist=Dist.EXPONENTIAL, seed=42, skew_to_first=2.0,
    target_utilization=0.55)
state = jax.device_put(state)
jax.block_until_ready(state.assignment)
print(f"build {time.time()-t0:.1f}s", flush=True)

cfg = CruiseControlConfig()
opt = GoalOptimizer(cfg, mesh="auto")
for name in ("warm", "steady"):
    t0 = time.time()
    _, res = opt.optimizations(state, meta, goals=goals_by_priority(cfg))
    print(f"{name}: {time.time()-t0:.2f}s proposals={len(res.proposals)} "
          f"bal={res.balancedness_after:.2f} "
          f"violated={res.violated_goals_after}", flush=True)
